"""Reference-format DeepSpeed checkpoint importer.

The migration story for existing DeepSpeed users: read a checkpoint written
by the reference engine (``deepspeed/runtime/engine.py:3050`` save layout —
``latest`` tag file, ``mp_rank_XX_model_states.pt`` module files, per-DP-rank
``*zero_pp_rank_{dp}_mp_rank_{tp}_optim_states.pt`` ZeRO shards) straight
into this framework's engine state: fp32 master params, Adam moments, and
step counters.

The ZeRO shard reconstruction follows the reference's own offline merge
protocol (``deepspeed/utils/zero_to_fp32.py:256,390`` and
``checkpoint/ds_to_universal.py:87``):

* stage <= 2 — each param group is ONE flat fp32 buffer partitioned
  contiguously across DP ranks: concatenate rank partitions, then slice
  sequentially by the ``param_shapes`` ordered dict saved in the module
  file (trailing 2·world alignment padding tolerated).
* stage 3 — params are interleaved: every param occupies
  ``ceil(numel/world)`` elements at a COMMON offset in every rank's flat
  buffer; zip the rank narrows and drop the tail padding.

Adam moments (``base_optimizer_state``) use the same layouts and merge the
same way. Torch pickles inside real checkpoints may reference deepspeed
classes (loss scalers, fragment addresses); minimal unpickle shims are
installed so ``torch.load`` succeeds without deepspeed present.
"""
import glob
import os
import re
import sys
import types
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..utils.logging import log_dist, logger

LATEST = "latest"
MODEL_SUFFIX = "_model_states.pt"
OPTIM_SUFFIX = "_optim_states.pt"

# reference checkpoint/constants.py key names
OPTIMIZER_STATE_DICT = "optimizer_state_dict"
SINGLE_PARTITION = "single_partition_of_fp32_groups"
FP32_FLAT_GROUPS = "fp32_flat_groups"
BASE_OPTIMIZER_STATE = "base_optimizer_state"
PARAM_SHAPES = "param_shapes"
PARTITION_COUNT = "partition_count"
ZERO_STAGE_KEY = "zero_stage"
# TP merge-rule keys (reference checkpoint/constants.py:54,77-82), stored in
# the module file by Megatron-DeepSpeed trainers
UNIVERSAL_CHECKPOINT_INFO = "universal_checkpoint_info"
TP_REPLICATED = "tp_replicated_parameter_patterns"
TO_AVERAGE = "parameter_to_average_patterns"
ROW_PARALLEL = "parameter_with_row_parallelism_patterns"
VOCAB_PARAMS = "vocabulary_parameter_patterns"
TWO_SUB_CAT0 = "parameter_with_2_sub_params_cat_dim_0"

_OPTIM_RE = re.compile(
    r"(?:bf16_|fp16_)?zero_pp_rank_(\d+)_mp_rank_(\d+)" +
    re.escape(OPTIM_SUFFIX) + r"$")


import contextlib


@contextlib.contextmanager
def _unpickle_shims():
    """TEMPORARILY stub the deepspeed classes reference pickles may name, so
    torch.load of a real checkpoint works without deepspeed installed.

    Scoped (not persistent): a lingering fake ``deepspeed`` in sys.modules
    makes ``transformers.is_deepspeed_available()`` true and breaks every
    subsequent HF import in the process. Unpickled instances keep their
    (stub) class references after the modules are removed — only the module
    table is restored."""
    try:
        import deepspeed  # noqa: F401 — real package present
        have_deepspeed = True
    except ImportError:
        have_deepspeed = False
    if have_deepspeed:
        # nothing to shim; yield OUTSIDE any try/except so an ImportError
        # raised by the wrapped body propagates instead of being swallowed
        yield
        return

    class _Stub:
        def __init__(self, *a, **k):
            self.__dict__.update(k)

        def __setstate__(self, state):
            if isinstance(state, dict):
                self.__dict__.update(state)

    shims = {
        "deepspeed.runtime.fp16.loss_scaler": ["LossScaler",
                                               "DynamicLossScaler"],
        "deepspeed.utils.tensor_fragment": ["fragment_address",
                                            "tensor_fragment"],
        "deepspeed.runtime.zero.config": ["ZeroStageEnum"],
    }
    installed = []
    names = ["deepspeed"]
    for mod_name in shims:
        parts = mod_name.split(".")
        names.extend(".".join(parts[:i]) for i in range(2, len(parts) + 1))
    for name in names:
        if name not in sys.modules:
            sys.modules[name] = types.ModuleType(name)
            installed.append(name)
    for mod_name, classes in shims.items():
        mod = sys.modules[mod_name]
        for cls in classes:
            if not hasattr(mod, cls):
                setattr(mod, cls, type(cls, (_Stub,), {}))
    try:
        yield
    finally:
        for name in installed:
            sys.modules.pop(name, None)


def _torch_load(path: str):
    import torch

    with _unpickle_shims():
        return torch.load(path, map_location="cpu", weights_only=False)


def _to_np(t) -> np.ndarray:
    import torch

    if isinstance(t, torch.Tensor):
        t = t.detach().cpu()
        if t.dtype == torch.bfloat16:
            t = t.float()
        return t.numpy()
    return np.asarray(t)


class DeepSpeedCheckpoint:
    """Inspector over a reference-format checkpoint directory (analog of
    ``deepspeed/checkpoint/deepspeed_checkpoint.py:1``)."""

    def __init__(self, ckpt_dir: str, tag: Optional[str] = None,
                 tp_rules: Optional[Dict[str, Any]] = None):
        """``tp_rules``: TP merge-rule pattern lists (reference
        ``universal_checkpoint_info`` keys — tp_replicated/-to-average/
        row-parallelism/vocabulary/2-sub-params patterns); defaults to the
        info embedded in the module file when present."""
        self.root = ckpt_dir
        if tag is None:
            latest = os.path.join(ckpt_dir, LATEST)
            if not os.path.exists(latest):
                raise FileNotFoundError(
                    f"{latest} missing — pass tag= explicitly (reference "
                    f"'latest' tag-pointer protocol)")
            with open(latest) as f:
                tag = f.read().strip()
        self.tag = tag
        self.dir = os.path.join(ckpt_dir, tag)
        if not os.path.isdir(self.dir):
            raise FileNotFoundError(f"no checkpoint directory {self.dir}")
        self.model_files = sorted(glob.glob(
            os.path.join(self.dir, f"mp_rank_*{MODEL_SUFFIX}")))
        if not self.model_files:
            raise FileNotFoundError(
                f"no mp_rank_*{MODEL_SUFFIX} under {self.dir}")
        if glob.glob(os.path.join(self.dir, "layer_*")):
            raise NotImplementedError(
                "pipeline-partitioned (layer_*) reference checkpoints are "
                "not supported; consolidate with the reference's "
                "ds_to_universal first")
        self.optim_files = sorted(glob.glob(
            os.path.join(self.dir, f"*zero_pp_rank_*{OPTIM_SUFFIX}")))
        self.tp_degree = len(self.model_files)
        self._model_sd = [_torch_load(f) for f in self.model_files]
        # optim files keyed (tp -> dp-ordered paths)
        self._optim_paths: Dict[int, List[str]] = {}
        for f in self.optim_files:
            m = _OPTIM_RE.search(os.path.basename(f))
            if not m:
                continue
            dp, tp = int(m.group(1)), int(m.group(2))
            self._optim_paths.setdefault(tp, []).append((dp, f))
        for tp in self._optim_paths:
            self._optim_paths[tp] = [f for _, f in
                                     sorted(self._optim_paths[tp])]
        self._optim_cache: Dict[int, List[Dict]] = {}
        self._tp_rules = tp_rules if tp_rules is not None else \
            self._model_sd[0].get(UNIVERSAL_CHECKPOINT_INFO) or {}

    # ------------------------------------------------------------ module side
    def module_state_dict(self, tp_rank: int = 0) -> Dict[str, np.ndarray]:
        """The saved module weights (compute precision) of one TP rank."""
        return {k: _to_np(v)
                for k, v in self._model_sd[tp_rank]["module"].items()}

    @property
    def param_shapes(self) -> List[Dict[str, tuple]]:
        return self.param_shapes_of(0)

    def param_shapes_of(self, tp_rank: int) -> List[Dict[str, tuple]]:
        """This TP rank's LOCAL param shapes (each rank flattens its own
        slices; shapes differ across ranks for TP-partitioned params)."""
        shapes = self._model_sd[tp_rank].get(PARAM_SHAPES)
        if shapes is None:
            raise ValueError(
                "checkpoint carries no param_shapes — written by a "
                "pre-0.3 DeepSpeed? (reference parse_model_states "
                "requirement)")
        if isinstance(shapes, dict):
            shapes = [shapes]
        return [{k: tuple(v) for k, v in group.items()} for group in shapes]

    @property
    def global_steps(self) -> int:
        return int(self._model_sd[0].get("global_steps", 0) or 0)

    @property
    def ds_version(self) -> Optional[str]:
        return self._model_sd[0].get("ds_version")

    # -------------------------------------------------------------- zero side
    def _load_optim(self, tp_rank: int = 0) -> List[Dict]:
        if tp_rank not in self._optim_cache:
            paths = self._optim_paths.get(tp_rank, [])
            self._optim_cache[tp_rank] = [
                _torch_load(f)[OPTIMIZER_STATE_DICT] for f in paths]
        return self._optim_cache[tp_rank]

    @property
    def zero_stage(self) -> int:
        if not self.optim_files:
            return 0
        return int(self._load_optim()[0].get(ZERO_STAGE_KEY, 1))

    @property
    def dp_degree(self) -> int:
        if not self.optim_files:
            return 1
        # fallback counts ONE tp rank's files — len(optim_files) would be
        # dp*tp and report a wrong degree for TP>1 checkpoints
        pc = self._load_optim()[0].get(PARTITION_COUNT,
                                       len(self._optim_paths.get(0, [])))
        return max(pc) if isinstance(pc, (list, tuple)) else int(pc)

    def _merge_stage2(self, per_rank_groups: List[List],
                      param_shapes: List[Dict[str, tuple]]
                      ) -> Dict[str, np.ndarray]:
        """Contiguous-partition merge (zero_to_fp32.py:256)."""
        out: Dict[str, np.ndarray] = {}
        n_groups = len(per_rank_groups[0])
        for g in range(n_groups):
            flat = np.concatenate([_to_np(r[g]).astype(np.float32).ravel()
                                   for r in per_rank_groups])
            offset = 0
            for name, shape in param_shapes[g].items():
                n = int(np.prod(shape)) if shape else 1
                out[name] = flat[offset:offset + n].reshape(shape)
                offset += n
            # trailing alignment padding (<= 2*world) is legal; more means
            # the shapes don't describe this buffer
            world = len(per_rank_groups)
            align = 2 * world
            if -(-offset // align) * align != -(-len(flat) // align) * align:
                raise ValueError(
                    f"group {g}: consumed {offset} of {len(flat)} elements "
                    f"— param_shapes do not match the flat partitions")
        return out

    def _merge_stage3(self, per_rank_flat: List[np.ndarray],
                      param_shapes: List[Dict[str, tuple]]
                      ) -> Dict[str, np.ndarray]:
        """Interleaved-partition merge (zero_to_fp32.py:390)."""
        world = len(per_rank_flat)
        shapes = {k: v for group in param_shapes
                  for k, v in group.items()}
        out: Dict[str, np.ndarray] = {}
        offset = 0
        for name, shape in shapes.items():
            n = int(np.prod(shape)) if shape else 1
            per = -(-n // world)  # ceil: every rank holds `per`, padded
            full = np.concatenate([r[offset:offset + per]
                                   for r in per_rank_flat])
            out[name] = full[:n].reshape(shape)
            offset += per
        return out

    def _zero_fp32_of(self, tp_rank: int) -> Dict[str, np.ndarray]:
        """One TP rank's dp-merged fp32 master (local TP slices)."""
        optim = self._load_optim(tp_rank)
        shapes = self.param_shapes_of(tp_rank)
        if self.zero_stage <= 2:
            groups = [sd[SINGLE_PARTITION] for sd in optim]
            return self._merge_stage2(groups, shapes)
        flats = [np.concatenate([_to_np(t).astype(np.float32).ravel()
                                 for t in sd[FP32_FLAT_GROUPS]])
                 for sd in optim]
        return self._merge_stage3(flats, shapes)

    def fp32_state_dict(self) -> Dict[str, np.ndarray]:
        """Merged full fp32 master weights (the zero_to_fp32 product,
        TP slices merged per the universal-checkpoint rules)."""
        if not self.optim_files:
            per_tp = [{k: v.astype(np.float32)
                       for k, v in self.module_state_dict(t).items()}
                      for t in range(self.tp_degree)]
        else:
            per_tp = [self._zero_fp32_of(t) for t in range(self.tp_degree)]
        return self._tp_merge(per_tp)

    def optimizer_moments(self) -> Dict[str, Dict[str, np.ndarray]]:
        """{'exp_avg': {name: arr}, 'exp_avg_sq': {name: arr}} merged the
        same way the fp32 weights are."""
        if not self.optim_files:
            return {}
        if not self._load_optim(0) or \
                not self._load_optim(0)[0].get(BASE_OPTIMIZER_STATE):
            return {}
        out: Dict[str, Dict[str, np.ndarray]] = {}
        stage = self.zero_stage
        for key in ("exp_avg", "exp_avg_sq"):
            try:
                per_tp = [self._zero_moment_of(t, key, stage)
                          for t in range(self.tp_degree)]
                out[key] = self._tp_merge(per_tp)
            except (KeyError, TypeError) as e:
                logger.warning("moment %s not importable (%s) — optimizer "
                               "state starts fresh", key, e)
        return out

    def _zero_moment_of(self, tp_rank: int, key: str, stage: int
                        ) -> Dict[str, np.ndarray]:
        optim = self._load_optim(tp_rank)
        shapes = self.param_shapes_of(tp_rank)

        def rank_groups(sd):
            b = sd[BASE_OPTIMIZER_STATE]
            groups = (b["state"] if isinstance(b, dict) and "state" in b
                      else b)
            if isinstance(groups, dict):
                groups = [groups[k] for k in sorted(groups)]
            return groups

        if stage <= 2:
            per_rank = [[g[key] for g in rank_groups(sd)] for sd in optim]
            return self._merge_stage2(per_rank, shapes)
        flats = [np.concatenate([_to_np(g[key]).astype(np.float32).ravel()
                                 for g in rank_groups(sd)])
                 for sd in optim]
        return self._merge_stage3(flats, shapes)


    # ------------------------------------------------------------- TP merge
    def _tp_merge(self, per_tp: List[Dict[str, np.ndarray]]
                  ) -> Dict[str, np.ndarray]:
        """Merge one-name-per-dict TP slices into full tensors per the
        universal-checkpoint pattern rules (reference
        ``ds_to_universal.merge_tp_slices``, ``checkpoint/
        ds_to_universal.py:160``): replicated → verify + take first;
        to-average → mean; 2-sub-params → chunk each slice in two and cat
        chunk-wise on dim 0 (fused gate/up or kv layouts); row-parallel →
        cat dim 1; default → cat dim 0; vocabulary params → strip padding
        to original_vocab_size."""
        if len(per_tp) == 1:
            return per_tp[0]
        rules = self._tp_rules
        if not rules:
            raise NotImplementedError(
                f"TP-partitioned checkpoint (tp={len(per_tp)}) carries no "
                f"universal_checkpoint_info merge rules — pass tp_rules= "
                f"(pattern lists: {TP_REPLICATED}, {TO_AVERAGE}, "
                f"{ROW_PARALLEL}, {VOCAB_PARAMS}, {TWO_SUB_CAT0}) or "
                f"consolidate with the reference's ds_to_universal first")

        def matched(patterns, name):
            return any(re.match(p, name) for p in (patterns or []))

        out: Dict[str, np.ndarray] = {}
        for name in per_tp[0]:
            slices = [d[name] for d in per_tp]
            if matched(rules.get(TP_REPLICATED), name):
                for other in slices[1:]:
                    if not np.array_equal(slices[0], other):
                        raise ValueError(
                            f"{name}: declared TP-replicated but slices "
                            f"differ across ranks")
                merged = slices[0]
            elif matched(rules.get(TO_AVERAGE), name):
                merged = np.mean(np.stack(slices), axis=0)
            elif matched(rules.get(TWO_SUB_CAT0), name):
                halves = [np.split(sl, 2, axis=0) for sl in slices]
                merged = np.concatenate(
                    [h[0] for h in halves] + [h[1] for h in halves], axis=0)
            elif matched(rules.get(ROW_PARALLEL), name):
                merged = np.concatenate(slices, axis=1)
            else:
                merged = np.concatenate(slices, axis=0)
            if matched(rules.get(VOCAB_PARAMS), name):
                orig = rules.get("original_vocab_size")
                if orig:
                    merged = merged[:int(orig)]
            out[name] = merged
        return out


def default_name_map(torch_name: str) -> str:
    """torch dotted module path → our '/'-separated pytree path."""
    return torch_name.replace(".", "/")


def load_deepspeed_checkpoint(engine, load_dir: str,
                              tag: Optional[str] = None,
                              name_map: Optional[Callable[[str], str]] = None,
                              load_optimizer_states: bool = True,
                              strict: bool = True,
                              tp_rules: Optional[Dict[str, Any]] = None
                              ) -> str:
    """Import a reference-format checkpoint into a live engine
    (the migration analog of ``engine.load_checkpoint``,
    reference ``runtime/engine.py:2688``).

    ``name_map(torch_name) -> engine param path`` (default: dots→slashes;
    return None to skip a tensor). Returns the resolved tag."""
    from ..utils.tensor_fragment import (param_paths,
                                         safe_set_full_fp32_param,
                                         safe_set_full_optimizer_state)

    ckpt = DeepSpeedCheckpoint(load_dir, tag, tp_rules=tp_rules)
    nm = name_map or default_name_map
    known = set(param_paths(engine.params))
    fp32 = ckpt.fp32_state_dict()
    mapped: Dict[str, np.ndarray] = {}
    skipped: List[str] = []
    for name, arr in fp32.items():
        path = nm(name)
        if path is None:
            continue
        if path not in known:
            skipped.append(name)
            continue
        mapped[path] = arr
    if skipped and strict:
        raise KeyError(
            f"{len(skipped)} checkpoint tensors have no engine param "
            f"(first: {skipped[:4]}); pass name_map= or strict=False")
    missing = known - set(mapped)
    if missing and strict:
        raise KeyError(f"{len(missing)} engine params absent from the "
                       f"checkpoint (first: {sorted(missing)[:4]})")
    for path, arr in mapped.items():
        safe_set_full_fp32_param(engine, path, arr)
    n_moments = 0
    if load_optimizer_states:
        moments = ckpt.optimizer_moments()
        for key, tree in moments.items():
            for name, arr in tree.items():
                path = nm(name)
                if path in mapped:
                    safe_set_full_optimizer_state(engine, path, arr, key)
                    n_moments += 1
        if moments and ckpt.global_steps:
            # Adam bias correction must resume at the checkpoint's step
            from ..utils.tensor_fragment import set_optimizer_step

            set_optimizer_step(engine, ckpt.global_steps)
    engine.global_steps = ckpt.global_steps
    log_dist(f"imported DeepSpeed checkpoint {ckpt.dir} "
             f"(ds_version={ckpt.ds_version}, zero_stage={ckpt.zero_stage}, "
             f"dp={ckpt.dp_degree}, {len(mapped)} params, "
             f"{n_moments} moment tensors, step={ckpt.global_steps})")
    return ckpt.tag


__all__ = ["DeepSpeedCheckpoint", "load_deepspeed_checkpoint",
           "default_name_map"]
