"""Universal checkpoint: inspection + topology-free export.

Reference machinery (``deepspeed/checkpoint/``, 1460 LoC):
``ds_to_universal.py`` merges per-rank ZeRO shards and TP slices into
per-parameter canonical files so a run can resume on a different topology;
``deepspeed_checkpoint.py`` (``DeepSpeedCheckpoint``) inspects sharded
checkpoint directories; ``universal_checkpoint.py`` hooks the resharded load.

Here the storage format is ALREADY canonical — ``checkpoint/engine.py`` writes
whole logical arrays and reshards on load against the caller's mesh — so the
conversion step vanishes. What remains useful and is provided:

* :class:`DSTpuCheckpoint` — inspector: leaf names/shapes/dtypes + run metadata
  without loading arrays (reads the JSON index only).
* :func:`load_state_dict` — pull any subset of leaves as host numpy arrays
  (the "extract_zero_shard_files + merge" path collapsed to a file read).
"""
import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from .engine import DATA_FILE, INDEX_FILE, META_FILE


class DSTpuCheckpoint:
    """Inspect a checkpoint directory (reference ``DeepSpeedCheckpoint``,
    ``deepspeed/checkpoint/deepspeed_checkpoint.py``)."""

    def __init__(self, ckpt_dir: str, tag: Optional[str] = None):
        self._data = None  # first: __del__ may run after a failed __init__
        if tag is None:
            latest = os.path.join(ckpt_dir, "latest")
            if os.path.exists(latest):
                with open(latest) as f:
                    tag = f.read().strip()
        self.dir = os.path.join(ckpt_dir, tag) if tag else ckpt_dir
        index_path = os.path.join(self.dir, INDEX_FILE)
        if not os.path.exists(index_path):
            raise FileNotFoundError(
                f"no {INDEX_FILE} under {self.dir} — not a dstpu checkpoint "
                f"(multi-host orbax checkpoints carry their own metadata)")
        with open(index_path) as f:
            self.index: List[dict] = json.load(f)
        self._by_name = {e["name"]: e for e in self.index}
        meta_path = os.path.join(self.dir, META_FILE)
        self.meta: Dict = {}
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                self.meta = json.load(f)
    def leaf_names(self, prefix: str = "") -> List[str]:
        return [e["name"] for e in self.index if e["name"].startswith(prefix)]

    def shape(self, name: str):
        return tuple(self._by_name[name]["shape"])

    def dtype(self, name: str) -> str:
        return self._by_name[name]["dtype"]

    @property
    def global_steps(self) -> int:
        return int(self.meta.get("global_steps", 0))

    @property
    def zero_stage(self) -> int:
        return int(self.meta.get("config", {}).get("zero_stage", 0))

    def num_parameters(self, prefix: str = "params") -> int:
        return sum(int(np.prod(e["shape"]))
                   for e in self.index if e["name"].startswith(prefix))

    def read(self, name: str) -> np.ndarray:
        e = self._by_name[name]
        if self._data is None:  # one open + OS page cache for all reads
            self._data = open(os.path.join(self.dir, DATA_FILE), "rb")
        self._data.seek(e["offset"])
        buf = self._data.read(e["nbytes"])
        return np.frombuffer(buf, dtype=np.dtype(e["dtype"])).reshape(e["shape"])

    def close(self):
        if getattr(self, "_data", None) is not None:
            self._data.close()
            self._data = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def __del__(self):  # best-effort
        self.close()


def load_state_dict(ckpt_dir: str, tag: Optional[str] = None,
                    prefix: str = "params",
                    names: Optional[Sequence[str]] = None
                    ) -> Dict[str, np.ndarray]:
    """Flat {leaf-name: array} for a checkpoint subset — the universal,
    topology-free view every converter/exporter builds on."""
    with DSTpuCheckpoint(ckpt_dir, tag) as ck:
        wanted = list(names) if names is not None else ck.leaf_names(prefix)
        return {n: ck.read(n) for n in wanted}
