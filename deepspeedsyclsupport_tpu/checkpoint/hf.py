"""HuggingFace checkpoint ingestion — serve/train real pretrained weights.

The TPU-native analog of the reference's model-integration stack:

* the 19 per-architecture policies that map HF module trees onto fused
  containers (``deepspeed/module_inject/containers/{llama,gpt2,opt,bloom,
  gptneox,gptj,...}.py``, ``replace_module.py:182``),
* the v2 checkpoint engines streaming HF shards
  (``deepspeed/inference/v2/checkpoint/huggingface_engine.py:1``), and
* the flat-parameter mapping DSL (``inference/v2/model_implementations/
  layer_container_base.py``, ``flat_model_helpers.py``).

Because the framework owns the model definition (``models/transformer.py``),
a "policy" collapses to a *leaf map*: our pytree leaf path → (HF tensor name,
transform). Transforms cover the orientation transpose (torch ``nn.Linear``
stores ``[out, in]``; our einsums contract ``[in, out]``), Conv1D's already-
``[in, out]`` layout (GPT-2), fused-QKV splits in each family's layout
(BLOOM/NeoX per-head ``[H, 3, hd]``, Falcon's q-then-kv concat), and GPT-J's
interleaved-rotary → split-half column permutation. Streaming discipline:
tensors are read one at a time from safetensors/torch shards, assembled
per-leaf (stacked layer leaves are filled layer by layer), pushed to device
against the target sharding, and the host buffer freed — peak host memory is
one stacked leaf, never the model.

Supported families: Llama/-2/-3 (incl. attention_bias / InternLM layout),
Mistral, Mixtral (MoE), Qwen2, GPT-2, GPT-Neo (alternating local attention,
unscaled logits), OPT, BLOOM, Falcon (multi-query), GPT-NeoX, GPT-J, Phi —
decoder side; BERT / DistilBERT / CLIP load via the encoder loaders below —
the superset of what the reference's module_inject + FastGen zoos serve.
"""
import json
import os
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .engine import _key_str
from ..models.config import ModelConfig
from ..utils.logging import log_dist, logger

__all__ = ["config_from_hf", "load_hf_checkpoint", "HFCheckpointSource"]

SAFE_INDEX = "model.safetensors.index.json"
SAFE_SINGLE = "model.safetensors"
BIN_INDEX = "pytorch_model.bin.index.json"
BIN_SINGLE = "pytorch_model.bin"
# top-level module prefixes that HF exports variously carry or drop
_MODULE_PREFIXES = ("transformer.", "model.", "gpt_neox.", "bert.",
                    "distilbert.")


# --------------------------------------------------------------------- config
def _map_activation(act: str) -> str:
    """HF ``hidden_act``/``activation_function`` → ours. HF's bare "gelu" is
    the exact erf form; "gelu_new"/"gelu_fast"/"gelu_pytorch_tanh" are tanh
    approximations. Unknown values raise — silently substituting would load
    cleanly and generate garbage."""
    known = {"silu": "silu", "swish": "silu",
             "gelu": "gelu_exact",
             "gelu_new": "gelu", "gelu_fast": "gelu",
             "gelu_pytorch_tanh": "gelu",
             "relu": "relu"}
    if act not in known:
        raise ValueError(
            f"unsupported hidden_act {act!r} (supported: {sorted(known)})")
    return known[act]


def config_from_hf(hf: Dict[str, Any], **overrides) -> ModelConfig:
    """HF ``config.json`` dict → :class:`ModelConfig` — the config half of the
    per-arch policy (reference containers read the same fields)."""
    mt = hf.get("model_type", "llama")
    eps = float(hf.get("rms_norm_eps",
                       hf.get("layer_norm_epsilon",
                              hf.get("layer_norm_eps", 1e-5))))
    if mt == "gpt2":
        d = hf.get("n_embd", 768)
        kw = dict(vocab_size=hf.get("vocab_size", 50257), hidden_size=d,
                  intermediate_size=hf.get("n_inner") or 4 * d,
                  num_layers=hf.get("n_layer", 12),
                  num_heads=hf.get("n_head", 12),
                  max_seq_len=hf.get("n_positions", 1024),
                  tie_embeddings=True, norm_type="layernorm",
                  pos_embed="learned", mlp_type="mlp", use_bias=True,
                  rms_norm_eps=eps,
                  activation=_map_activation(
                      hf.get("activation_function", "gelu_new")))
    elif mt == "gpt_neo":
        d = hf.get("hidden_size", 2048)
        # attention_types expands to a per-layer global/local pattern
        # (HF GPTNeoConfig.expand_attention_types_params)
        pattern = []
        for item in hf.get("attention_types", [[["global", "local"], 12]]):
            for _ in range(item[1]):
                pattern.extend(item[0])
        win = hf.get("window_size", 256)
        kw = dict(vocab_size=hf.get("vocab_size", 50257), hidden_size=d,
                  intermediate_size=hf.get("intermediate_size") or 4 * d,
                  num_layers=hf.get("num_layers", 24),
                  num_heads=hf.get("num_heads", 16),
                  max_seq_len=hf.get("max_position_embeddings", 2048),
                  tie_embeddings=True, norm_type="layernorm",
                  pos_embed="learned", mlp_type="mlp", use_bias=True,
                  qkv_bias=False,
                  attn_scale=1.0,  # GPT-Neo does NOT scale logits by 1/sqrt(d)
                  attn_windows=tuple(win if t == "local" else None
                                     for t in pattern),
                  rms_norm_eps=eps,
                  activation=_map_activation(
                      hf.get("activation_function", "gelu_new")))
    elif mt == "opt":
        kw = dict(vocab_size=hf.get("vocab_size", 50272),
                  hidden_size=hf.get("hidden_size", 768),
                  intermediate_size=hf.get("ffn_dim",
                                           4 * hf.get("hidden_size", 768)),
                  num_layers=hf.get("num_hidden_layers", 12),
                  num_heads=hf.get("num_attention_heads", 12),
                  max_seq_len=hf.get("max_position_embeddings", 2048),
                  tie_embeddings=bool(hf.get("tie_word_embeddings", True)),
                  norm_type="layernorm", pos_embed="learned",
                  pos_embed_offset=2, mlp_type="mlp", use_bias=True,
                  rms_norm_eps=eps,
                  activation=_map_activation(
                      hf.get("activation_function", "relu")))
        if not hf.get("do_layer_norm_before", True):
            raise ValueError("post-layernorm OPT (do_layer_norm_before="
                             "False, 125m/350m) is not supported")
        wepd = hf.get("word_embed_proj_dim")
        if wepd is not None and wepd != hf.get("hidden_size", 768):
            raise ValueError(
                f"OPT word_embed_proj_dim={wepd} != hidden_size — the "
                f"project_in/project_out variant is not supported")
    elif mt == "bloom":
        d = hf.get("hidden_size", hf.get("n_embed", 1024))
        kw = dict(vocab_size=hf.get("vocab_size", 250880), hidden_size=d,
                  intermediate_size=4 * d,
                  num_layers=hf.get("n_layer",
                                    hf.get("num_hidden_layers", 24)),
                  num_heads=hf.get("n_head",
                                   hf.get("num_attention_heads", 16)),
                  max_seq_len=2048,
                  tie_embeddings=True, norm_type="layernorm",
                  pos_embed="alibi", mlp_type="mlp", use_bias=True,
                  embed_norm=True, rms_norm_eps=eps, activation="gelu")
    elif mt == "falcon":
        if hf.get("new_decoder_architecture", False):
            raise ValueError("falcon new_decoder_architecture (40b/180b "
                             "grouped-qkv interleave) is not supported yet")
        d = hf.get("hidden_size", 4544)
        n = hf.get("num_attention_heads", hf.get("n_head", 71))
        kw = dict(vocab_size=hf.get("vocab_size", 65024), hidden_size=d,
                  intermediate_size=4 * d,
                  num_layers=hf.get("num_hidden_layers",
                                    hf.get("n_layer", 32)),
                  num_heads=n,
                  num_kv_heads=1 if hf.get("multi_query", True) else n,
                  max_seq_len=hf.get("max_position_embeddings", 2048),
                  tie_embeddings=bool(hf.get("tie_word_embeddings", True)),
                  norm_type="layernorm", mlp_type="mlp",
                  activation="gelu_exact", use_bias=bool(hf.get("bias",
                                                                False)),
                  # falcon-rw family: ALiBi instead of RoPE. HF falcon folds
                  # the softmax scale over the bias too — softmax((qk+alibi)/
                  # √hd) — unlike bloom, so the effective slopes are /√hd
                  pos_embed="alibi" if hf.get("alibi") else "rope",
                  alibi_scale=(1.0 / float(np.sqrt(d // n))
                               if hf.get("alibi") else 1.0),
                  parallel_block=bool(hf.get("parallel_attn", True)),
                  shared_block_norm=bool(hf.get("parallel_attn", True)),
                  rope_theta=float(hf.get("rope_theta", 10000.0)),
                  rms_norm_eps=eps)
    elif mt == "gpt_neox":
        d = hf.get("hidden_size", 6144)
        kw = dict(vocab_size=hf.get("vocab_size", 50432), hidden_size=d,
                  intermediate_size=hf.get("intermediate_size", 4 * d),
                  num_layers=hf.get("num_hidden_layers", 44),
                  num_heads=hf.get("num_attention_heads", 64),
                  max_seq_len=hf.get("max_position_embeddings", 2048),
                  tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
                  norm_type="layernorm", mlp_type="mlp", use_bias=True,
                  rotary_pct=float(hf.get("rotary_pct", 0.25)),
                  parallel_block=bool(hf.get("use_parallel_residual", True)),
                  rope_theta=float(hf.get("rotary_emb_base", 10000.0)),
                  rms_norm_eps=eps,
                  activation=_map_activation(hf.get("hidden_act", "gelu")))
    elif mt == "gptj":
        d = hf.get("n_embd", 4096)
        nh = hf.get("n_head", 16)
        kw = dict(vocab_size=hf.get("vocab_size", 50400), hidden_size=d,
                  intermediate_size=hf.get("n_inner") or 4 * d,
                  num_layers=hf.get("n_layer", 28), num_heads=nh,
                  max_seq_len=hf.get("n_positions", 2048),
                  tie_embeddings=False, norm_type="layernorm",
                  mlp_type="mlp", use_bias=True, qkv_bias=False,
                  attn_out_bias=False, lm_head_bias=True,
                  rotary_pct=hf.get("rotary_dim", 64) / (d // nh),
                  parallel_block=True, shared_block_norm=True,
                  rms_norm_eps=eps,
                  activation=_map_activation(
                      hf.get("activation_function", "gelu_new")))
    elif mt == "phi":
        d = hf.get("hidden_size", 2560)
        kw = dict(vocab_size=hf.get("vocab_size", 51200), hidden_size=d,
                  intermediate_size=hf.get("intermediate_size", 4 * d),
                  num_layers=hf.get("num_hidden_layers", 32),
                  num_heads=hf.get("num_attention_heads", 32),
                  num_kv_heads=hf.get("num_key_value_heads") or
                  hf.get("num_attention_heads", 32),
                  max_seq_len=hf.get("max_position_embeddings", 2048),
                  tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
                  norm_type="layernorm", mlp_type="mlp", use_bias=True,
                  lm_head_bias=True,
                  rotary_pct=float(hf.get("partial_rotary_factor", 0.5)),
                  parallel_block=True, shared_block_norm=True,
                  rope_theta=float(hf.get("rope_theta", 10000.0)),
                  rms_norm_eps=eps,
                  activation=_map_activation(hf.get("hidden_act",
                                                    "gelu_new")))
    else:
        # Llama / Mistral / Mixtral / Qwen2 family (the original map)
        kw = dict(
            vocab_size=hf.get("vocab_size", 32000),
            hidden_size=hf.get("hidden_size", 4096),
            intermediate_size=hf.get("intermediate_size", 11008),
            num_layers=hf.get("num_hidden_layers", 32),
            num_heads=hf.get("num_attention_heads", 32),
            num_kv_heads=hf.get("num_key_value_heads",
                                hf.get("num_attention_heads", 32)),
            head_dim=hf.get("head_dim"),
            max_seq_len=hf.get("max_position_embeddings", 4096),
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            rms_norm_eps=eps,
            tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
            activation=_map_activation(hf.get("hidden_act", "silu")),
        )
        if hf.get("sliding_window") and hf.get("use_sliding_window", True):
            kw["sliding_window"] = int(hf["sliding_window"])
        if mt == "qwen2":
            kw["qkv_bias"] = True
        if bool(hf.get("attention_bias", False)) or mt == "internlm":
            # llama attention_bias=True / InternLM-v1 ("bias": true): q/k/v
            # AND output projections carry biases
            kw["qkv_bias"] = True
            kw["attn_out_bias"] = True
        if mt == "mixtral" or "num_local_experts" in hf:
            kw.update(num_experts=hf.get("num_local_experts", 8),
                      num_experts_per_tok=hf.get("num_experts_per_tok", 2),
                      aux_loss_coef=float(hf.get("router_aux_loss_coef",
                                                 0.01)))
    kw.update(overrides)
    return ModelConfig(**kw)


# --------------------------------------------------------------------- source
class HFCheckpointSource:
    """Random access to the tensors of an HF checkpoint directory, reading
    lazily from safetensors (preferred) or torch ``.bin`` shards (the two
    layouts ``huggingface_engine.py`` handles)."""

    def __init__(self, path: str):
        self.path = path
        self._name_to_file: Dict[str, str] = {}
        self._safe_handles: Dict[str, Any] = {}
        self._bin_cache: Dict[str, Dict[str, Any]] = {}
        self._use_safetensors = True
        if os.path.exists(os.path.join(path, SAFE_INDEX)):
            with open(os.path.join(path, SAFE_INDEX)) as f:
                self._name_to_file = dict(json.load(f)["weight_map"])
        elif os.path.exists(os.path.join(path, SAFE_SINGLE)):
            from safetensors import safe_open

            with safe_open(os.path.join(path, SAFE_SINGLE),
                           framework="numpy") as f:
                self._name_to_file = {k: SAFE_SINGLE for k in f.keys()}
        elif os.path.exists(os.path.join(path, BIN_INDEX)):
            self._use_safetensors = False
            with open(os.path.join(path, BIN_INDEX)) as f:
                self._name_to_file = dict(json.load(f)["weight_map"])
        elif os.path.exists(os.path.join(path, BIN_SINGLE)):
            self._use_safetensors = False
            sd = self._load_bin(BIN_SINGLE)
            self._name_to_file = {k: BIN_SINGLE for k in sd}
        else:
            raise FileNotFoundError(
                f"no model.safetensors[.index.json] or pytorch_model.bin"
                f"[.index.json] under {path}")
        # Detect ONCE whether this checkpoint's names carry a top-level
        # module prefix, so resolve() maps in a single direction. Trying
        # both directions per tensor could silently load a different tensor
        # when a checkpoint contains both a prefixed and an unprefixed
        # tensor of the same suffix, masking a family-map bug.
        self._ckpt_prefix: Optional[str] = None
        for pre in _MODULE_PREFIXES:
            if any(n.startswith(pre) for n in self._name_to_file):
                self._ckpt_prefix = pre
                break

    @property
    def names(self) -> Iterable[str]:
        return self._name_to_file.keys()

    def __contains__(self, name: str) -> bool:
        return self.resolve(name) is not None

    def resolve(self, name: str) -> Optional[str]:
        """Checkpoint name variants: some exports carry/drop the top-level
        module prefix (``transformer.``/``model.``/``bert.``/...). The
        resolution direction is constrained per checkpoint (detected at
        index time): a prefixed checkpoint only gains its prefix on
        unprefixed lookups (plus the nested-module strip that reveals that
        same prefix); an unprefixed one only strips — so a wrong family
        map fails loudly instead of quietly mis-loading."""
        if name in self._name_to_file:
            return name
        # Strip one leading module level (encoder-only exports drop the
        # outermost module: 'distilbert.transformer.layer...' is stored as
        # 'transformer.layer...', 'distilbert.embeddings...' as
        # 'embeddings...'). The one strip that stays FORBIDDEN is removing
        # the checkpoint's own detected prefix — on a P-prefixed
        # checkpoint, resolving a missed 'P.x' lookup to an unrelated
        # unprefixed 'x' is exactly the quiet family-map mis-load this
        # detection exists to prevent.
        for pre in _MODULE_PREFIXES:
            if pre != self._ckpt_prefix and name.startswith(pre):
                stripped = name[len(pre):]
                if stripped in self._name_to_file:
                    return stripped
        if (self._ckpt_prefix is not None
                and not name.startswith(self._ckpt_prefix)):
            cand = self._ckpt_prefix + name
            if cand in self._name_to_file:
                return cand
        return None

    def _load_bin(self, fname: str) -> Dict[str, Any]:
        if fname not in self._bin_cache:
            import torch

            self._bin_cache[fname] = torch.load(
                os.path.join(self.path, fname), map_location="cpu",
                weights_only=True)
        return self._bin_cache[fname]

    def get(self, name: str) -> np.ndarray:
        """One tensor as numpy (bf16 arrives as ml_dtypes.bfloat16)."""
        resolved = self.resolve(name)
        if resolved is None:
            raise KeyError(f"tensor {name!r} not in checkpoint "
                           f"(have e.g. {list(self.names)[:4]}...)")
        fname = self._name_to_file[resolved]
        if self._use_safetensors:
            if fname not in self._safe_handles:
                from safetensors import safe_open

                self._safe_handles[fname] = safe_open(
                    os.path.join(self.path, fname), framework="numpy")
            return self._safe_handles[fname].get_tensor(resolved)
        t = self._load_bin(fname)[resolved]
        if str(t.dtype) == "torch.bfloat16":
            import ml_dtypes

            # torch has no numpy bridge for bf16: round-trip through fp32
            return t.float().numpy().astype(ml_dtypes.bfloat16)
        return t.numpy()

    def close(self):
        self._safe_handles.clear()
        self._bin_cache.clear()


# ------------------------------------------------------------------ transforms
def _t(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a.T)


def _id(a: np.ndarray) -> np.ndarray:
    return a


def _cols(lo: int, hi: int) -> Callable:
    """Slice columns of an already-[in, out] matrix (GPT-2 Conv1D fused qkv)."""
    return lambda a: np.ascontiguousarray(a[..., lo:hi])


def _fused3(idx: int, heads: int, head_dim: int) -> Callable:
    """BLOOM/NeoX fused qkv: weight [(H·3·hd), d] laid out [H, 3, hd] on the
    out dim → component ``idx`` as [d, H·hd]; bias [(H·3·hd)] → [H·hd]."""
    def f(a: np.ndarray) -> np.ndarray:
        if a.ndim == 2:
            w = a.reshape(heads, 3, head_dim, a.shape[1])[:, idx]
            return _t(w.reshape(heads * head_dim, a.shape[1]))
        return np.ascontiguousarray(
            a.reshape(heads, 3, head_dim)[:, idx].reshape(-1))
    return f


def _rows(lo: int, hi: int) -> Callable:
    """Row-slice of a torch [out, in] matrix then transpose (Falcon concat
    fused qkv: q rows, then k rows, then v rows)."""
    return lambda a: _t(a[lo:hi])


def _rotary_interleaved_to_half(heads: int, head_dim: int,
                                rotary_dim: int) -> Callable:
    """GPT-J stores rotary dims interleaved (pairs (0,1),(2,3),…); our
    :func:`models.layers.apply_rope` uses the split-half convention (pairs
    (i, i+rd/2)). Attention is invariant under a consistent permutation of
    q/k feature columns, so permuting the weight columns at load time makes
    the two conventions produce identical logits."""
    perm = np.concatenate([np.arange(0, rotary_dim, 2),
                           np.arange(1, rotary_dim, 2),
                           np.arange(rotary_dim, head_dim)])

    def f(a: np.ndarray) -> np.ndarray:
        w = _t(a)  # [d, H·hd]
        w = w.reshape(w.shape[0], heads, head_dim)[:, :, perm]
        return np.ascontiguousarray(w.reshape(w.shape[0], -1))
    return f


# ----------------------------------------------------------------- leaf maps
def _norm_leaves(segs: Tuple[str, ...], hf_base: str, cfg: ModelConfig):
    m = {segs + ("scale",): (hf_base + ".weight", _id)}
    if cfg.norm_type == "layernorm":
        m[segs + ("bias",)] = (hf_base + ".bias", _id)
    return m


def _family_llama(cfg: ModelConfig):
    def top():
        m = {("embed", "embedding"): ("model.embed_tokens.weight", _id)}
        m.update(_norm_leaves(("final_norm",), "model.norm", cfg))
        if not cfg.tie_embeddings:
            m[("lm_head", "kernel")] = ("lm_head.weight", _t)
        return m

    def layer(i: int):
        pre = f"model.layers.{i}."
        m = {
            ("attn", "wq"): (pre + "self_attn.q_proj.weight", _t),
            ("attn", "wk"): (pre + "self_attn.k_proj.weight", _t),
            ("attn", "wv"): (pre + "self_attn.v_proj.weight", _t),
            ("attn", "wo"): (pre + "self_attn.o_proj.weight", _t),
        }
        if cfg.qkv_bias:  # qwen2 / attention_bias / internlm
            m[("attn", "bq")] = (pre + "self_attn.q_proj.bias", _id)
            m[("attn", "bk")] = (pre + "self_attn.k_proj.bias", _id)
            m[("attn", "bv")] = (pre + "self_attn.v_proj.bias", _id)
        if cfg.attn_out_bias:
            m[("attn", "bo")] = (pre + "self_attn.o_proj.bias", _id)
        m.update(_norm_leaves(("attn_norm",), pre + "input_layernorm", cfg))
        m.update(_norm_leaves(("mlp_norm",), pre + "post_attention_layernorm",
                              cfg))
        if cfg.any_moe:
            m[("moe", "router")] = (pre + "block_sparse_moe.gate.weight", _t)
        else:
            m[("mlp", "w_gate")] = (pre + "mlp.gate_proj.weight", _t)
            m[("mlp", "w_up")] = (pre + "mlp.up_proj.weight", _t)
            m[("mlp", "w_down")] = (pre + "mlp.down_proj.weight", _t)
        return m

    return top, layer


def _family_gpt2(cfg: ModelConfig):
    d = cfg.hidden_size

    def top():
        m = {("embed", "embedding"): ("transformer.wte.weight", _id),
             ("pos_embed", "embedding"): ("transformer.wpe.weight", _id)}
        m.update(_norm_leaves(("final_norm",), "transformer.ln_f", cfg))
        return m

    def layer(i: int):
        pre = f"transformer.h.{i}."
        m = {
            # Conv1D already stores [in, out]: slice fused qkv columns
            ("attn", "wq"): (pre + "attn.c_attn.weight", _cols(0, d)),
            ("attn", "wk"): (pre + "attn.c_attn.weight", _cols(d, 2 * d)),
            ("attn", "wv"): (pre + "attn.c_attn.weight", _cols(2 * d, 3 * d)),
            ("attn", "bq"): (pre + "attn.c_attn.bias", _cols(0, d)),
            ("attn", "bk"): (pre + "attn.c_attn.bias", _cols(d, 2 * d)),
            ("attn", "bv"): (pre + "attn.c_attn.bias", _cols(2 * d, 3 * d)),
            ("attn", "wo"): (pre + "attn.c_proj.weight", _id),
            ("attn", "bo"): (pre + "attn.c_proj.bias", _id),
            ("mlp", "fc1"): (pre + "mlp.c_fc.weight", _id),
            ("mlp", "b1"): (pre + "mlp.c_fc.bias", _id),
            ("mlp", "fc2"): (pre + "mlp.c_proj.weight", _id),
            ("mlp", "b2"): (pre + "mlp.c_proj.bias", _id),
        }
        m.update(_norm_leaves(("attn_norm",), pre + "ln_1", cfg))
        m.update(_norm_leaves(("mlp_norm",), pre + "ln_2", cfg))
        return m

    return top, layer


def _family_gpt_neo(cfg: ModelConfig):
    def top():
        m = {("embed", "embedding"): ("transformer.wte.weight", _id),
             ("pos_embed", "embedding"): ("transformer.wpe.weight", _id)}
        m.update(_norm_leaves(("final_norm",), "transformer.ln_f", cfg))
        return m

    def layer(i: int):
        pre = f"transformer.h.{i}."
        # nn.Linear [out, in] -> transpose; q/k/v carry NO bias, out does
        m = {
            ("attn", "wq"): (pre + "attn.attention.q_proj.weight", _t),
            ("attn", "wk"): (pre + "attn.attention.k_proj.weight", _t),
            ("attn", "wv"): (pre + "attn.attention.v_proj.weight", _t),
            ("attn", "wo"): (pre + "attn.attention.out_proj.weight", _t),
            ("attn", "bo"): (pre + "attn.attention.out_proj.bias", _id),
            ("mlp", "fc1"): (pre + "mlp.c_fc.weight", _t),
            ("mlp", "b1"): (pre + "mlp.c_fc.bias", _id),
            ("mlp", "fc2"): (pre + "mlp.c_proj.weight", _t),
            ("mlp", "b2"): (pre + "mlp.c_proj.bias", _id),
        }
        m.update(_norm_leaves(("attn_norm",), pre + "ln_1", cfg))
        m.update(_norm_leaves(("mlp_norm",), pre + "ln_2", cfg))
        return m

    return top, layer


def _family_opt(cfg: ModelConfig):
    def top():
        m = {("embed", "embedding"): ("model.decoder.embed_tokens.weight",
                                      _id),
             ("pos_embed", "embedding"): (
                 "model.decoder.embed_positions.weight", _id)}
        m.update(_norm_leaves(("final_norm",),
                              "model.decoder.final_layer_norm", cfg))
        if not cfg.tie_embeddings:
            m[("lm_head", "kernel")] = ("lm_head.weight", _t)
        return m

    def layer(i: int):
        pre = f"model.decoder.layers.{i}."
        m = {
            ("attn", "wq"): (pre + "self_attn.q_proj.weight", _t),
            ("attn", "bq"): (pre + "self_attn.q_proj.bias", _id),
            ("attn", "wk"): (pre + "self_attn.k_proj.weight", _t),
            ("attn", "bk"): (pre + "self_attn.k_proj.bias", _id),
            ("attn", "wv"): (pre + "self_attn.v_proj.weight", _t),
            ("attn", "bv"): (pre + "self_attn.v_proj.bias", _id),
            ("attn", "wo"): (pre + "self_attn.out_proj.weight", _t),
            ("attn", "bo"): (pre + "self_attn.out_proj.bias", _id),
            ("mlp", "fc1"): (pre + "fc1.weight", _t),
            ("mlp", "b1"): (pre + "fc1.bias", _id),
            ("mlp", "fc2"): (pre + "fc2.weight", _t),
            ("mlp", "b2"): (pre + "fc2.bias", _id),
        }
        m.update(_norm_leaves(("attn_norm",), pre + "self_attn_layer_norm",
                              cfg))
        m.update(_norm_leaves(("mlp_norm",), pre + "final_layer_norm", cfg))
        return m

    return top, layer


def _family_bloom(cfg: ModelConfig):
    n, hd = cfg.num_heads, cfg.head_dim

    def top():
        m = {("embed", "embedding"): ("transformer.word_embeddings.weight",
                                      _id)}
        m.update(_norm_leaves(("embed_norm",),
                              "transformer.word_embeddings_layernorm", cfg))
        m.update(_norm_leaves(("final_norm",), "transformer.ln_f", cfg))
        return m

    def layer(i: int):
        pre = f"transformer.h.{i}."
        qkv_w = pre + "self_attention.query_key_value.weight"
        qkv_b = pre + "self_attention.query_key_value.bias"
        m = {
            ("attn", "wq"): (qkv_w, _fused3(0, n, hd)),
            ("attn", "wk"): (qkv_w, _fused3(1, n, hd)),
            ("attn", "wv"): (qkv_w, _fused3(2, n, hd)),
            ("attn", "bq"): (qkv_b, _fused3(0, n, hd)),
            ("attn", "bk"): (qkv_b, _fused3(1, n, hd)),
            ("attn", "bv"): (qkv_b, _fused3(2, n, hd)),
            ("attn", "wo"): (pre + "self_attention.dense.weight", _t),
            ("attn", "bo"): (pre + "self_attention.dense.bias", _id),
            ("mlp", "fc1"): (pre + "mlp.dense_h_to_4h.weight", _t),
            ("mlp", "b1"): (pre + "mlp.dense_h_to_4h.bias", _id),
            ("mlp", "fc2"): (pre + "mlp.dense_4h_to_h.weight", _t),
            ("mlp", "b2"): (pre + "mlp.dense_4h_to_h.bias", _id),
        }
        m.update(_norm_leaves(("attn_norm",), pre + "input_layernorm", cfg))
        m.update(_norm_leaves(("mlp_norm",), pre + "post_attention_layernorm",
                              cfg))
        return m

    return top, layer


def _family_falcon(cfg: ModelConfig):
    n, kv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    def top():
        m = {("embed", "embedding"): ("transformer.word_embeddings.weight",
                                      _id)}
        m.update(_norm_leaves(("final_norm",), "transformer.ln_f", cfg))
        if not cfg.tie_embeddings:
            m[("lm_head", "kernel")] = ("lm_head.weight", _t)
        return m

    def layer(i: int):
        pre = f"transformer.h.{i}."
        qkv = pre + "self_attention.query_key_value.weight"
        if kv == 1:
            # multi-query layout: q rows [n·hd], then k [kv·hd], then v
            q_fn = _rows(0, n * hd)
            k_fn = _rows(n * hd, (n + kv) * hd)
            v_fn = _rows((n + kv) * hd, (n + 2 * kv) * hd)
        else:
            # falcon-rw (multi_query=False): per-head [H, 3, hd] interleave
            q_fn, k_fn, v_fn = (_fused3(0, n, hd), _fused3(1, n, hd),
                                _fused3(2, n, hd))
        m = {
            ("attn", "wq"): (qkv, q_fn),
            ("attn", "wk"): (qkv, k_fn),
            ("attn", "wv"): (qkv, v_fn),
            ("attn", "wo"): (pre + "self_attention.dense.weight", _t),
            ("mlp", "fc1"): (pre + "mlp.dense_h_to_4h.weight", _t),
            ("mlp", "fc2"): (pre + "mlp.dense_4h_to_h.weight", _t),
        }
        if cfg.use_bias:
            qkv_b = pre + "self_attention.query_key_value.bias"
            if kv == 1:
                m[("attn", "bq")] = (qkv_b, lambda a: a[:n * hd])
                m[("attn", "bk")] = (qkv_b,
                                     lambda a: a[n * hd:(n + kv) * hd])
                m[("attn", "bv")] = (qkv_b,
                                     lambda a: a[(n + kv) * hd:])
            else:
                m[("attn", "bq")] = (qkv_b, _fused3(0, n, hd))
                m[("attn", "bk")] = (qkv_b, _fused3(1, n, hd))
                m[("attn", "bv")] = (qkv_b, _fused3(2, n, hd))
            m[("attn", "bo")] = (pre + "self_attention.dense.bias", _id)
            m[("mlp", "b1")] = (pre + "mlp.dense_h_to_4h.bias", _id)
            m[("mlp", "b2")] = (pre + "mlp.dense_4h_to_h.bias", _id)
        m.update(_norm_leaves(("attn_norm",), pre + "input_layernorm", cfg))
        if not cfg.shared_block_norm:
            m.update(_norm_leaves(("mlp_norm",), pre + "post_attention_"
                                  "layernorm", cfg))
        return m

    return top, layer


def _family_gpt_neox(cfg: ModelConfig):
    n, hd = cfg.num_heads, cfg.head_dim

    def top():
        m = {("embed", "embedding"): ("gpt_neox.embed_in.weight", _id)}
        m.update(_norm_leaves(("final_norm",), "gpt_neox.final_layer_norm",
                              cfg))
        if not cfg.tie_embeddings:
            m[("lm_head", "kernel")] = ("embed_out.weight", _t)
        return m

    def layer(i: int):
        pre = f"gpt_neox.layers.{i}."
        qkv_w = pre + "attention.query_key_value.weight"
        qkv_b = pre + "attention.query_key_value.bias"
        m = {
            ("attn", "wq"): (qkv_w, _fused3(0, n, hd)),
            ("attn", "wk"): (qkv_w, _fused3(1, n, hd)),
            ("attn", "wv"): (qkv_w, _fused3(2, n, hd)),
            ("attn", "bq"): (qkv_b, _fused3(0, n, hd)),
            ("attn", "bk"): (qkv_b, _fused3(1, n, hd)),
            ("attn", "bv"): (qkv_b, _fused3(2, n, hd)),
            ("attn", "wo"): (pre + "attention.dense.weight", _t),
            ("attn", "bo"): (pre + "attention.dense.bias", _id),
            ("mlp", "fc1"): (pre + "mlp.dense_h_to_4h.weight", _t),
            ("mlp", "b1"): (pre + "mlp.dense_h_to_4h.bias", _id),
            ("mlp", "fc2"): (pre + "mlp.dense_4h_to_h.weight", _t),
            ("mlp", "b2"): (pre + "mlp.dense_4h_to_h.bias", _id),
        }
        m.update(_norm_leaves(("attn_norm",), pre + "input_layernorm", cfg))
        m.update(_norm_leaves(("mlp_norm",), pre + "post_attention_layernorm",
                              cfg))
        return m

    return top, layer


def _family_gptj(cfg: ModelConfig):
    n, hd, rd = cfg.num_heads, cfg.head_dim, cfg.rotary_dim
    rot = _rotary_interleaved_to_half(n, hd, rd)

    def top():
        m = {("embed", "embedding"): ("transformer.wte.weight", _id)}
        if not cfg.tie_embeddings:
            m[("lm_head", "kernel")] = ("lm_head.weight", _t)
            if cfg.lm_head_bias:
                m[("lm_head", "bias")] = ("lm_head.bias", _id)
        m.update(_norm_leaves(("final_norm",), "transformer.ln_f", cfg))
        return m

    def layer(i: int):
        pre = f"transformer.h.{i}."
        m = {
            ("attn", "wq"): (pre + "attn.q_proj.weight", rot),
            ("attn", "wk"): (pre + "attn.k_proj.weight", rot),
            ("attn", "wv"): (pre + "attn.v_proj.weight", _t),
            ("attn", "wo"): (pre + "attn.out_proj.weight", _t),
            ("mlp", "fc1"): (pre + "mlp.fc_in.weight", _t),
            ("mlp", "b1"): (pre + "mlp.fc_in.bias", _id),
            ("mlp", "fc2"): (pre + "mlp.fc_out.weight", _t),
            ("mlp", "b2"): (pre + "mlp.fc_out.bias", _id),
        }
        m.update(_norm_leaves(("attn_norm",), pre + "ln_1", cfg))
        return m

    return top, layer


def _family_phi(cfg: ModelConfig):
    def top():
        m = {("embed", "embedding"): ("model.embed_tokens.weight", _id)}
        if not cfg.tie_embeddings:
            m[("lm_head", "kernel")] = ("lm_head.weight", _t)
            if cfg.lm_head_bias:
                m[("lm_head", "bias")] = ("lm_head.bias", _id)
        m.update(_norm_leaves(("final_norm",), "model.final_layernorm", cfg))
        return m

    def layer(i: int):
        pre = f"model.layers.{i}."
        m = {
            ("attn", "wq"): (pre + "self_attn.q_proj.weight", _t),
            ("attn", "bq"): (pre + "self_attn.q_proj.bias", _id),
            ("attn", "wk"): (pre + "self_attn.k_proj.weight", _t),
            ("attn", "bk"): (pre + "self_attn.k_proj.bias", _id),
            ("attn", "wv"): (pre + "self_attn.v_proj.weight", _t),
            ("attn", "bv"): (pre + "self_attn.v_proj.bias", _id),
            ("attn", "wo"): (pre + "self_attn.dense.weight", _t),
            ("attn", "bo"): (pre + "self_attn.dense.bias", _id),
            ("mlp", "fc1"): (pre + "mlp.fc1.weight", _t),
            ("mlp", "b1"): (pre + "mlp.fc1.bias", _id),
            ("mlp", "fc2"): (pre + "mlp.fc2.weight", _t),
            ("mlp", "b2"): (pre + "mlp.fc2.bias", _id),
        }
        m.update(_norm_leaves(("attn_norm",), pre + "input_layernorm", cfg))
        return m

    return top, layer


FAMILIES = {
    "llama": _family_llama, "mistral": _family_llama,
    "mixtral": _family_llama, "qwen2": _family_llama,
    "internlm": _family_llama,
    "gpt2": _family_gpt2, "gpt_neo": _family_gpt_neo,
    "opt": _family_opt, "bloom": _family_bloom,
    "falcon": _family_falcon, "gpt_neox": _family_gpt_neox,
    "gptj": _family_gptj, "phi": _family_phi,
}


def _expert_names(i: int, e: int) -> Dict[str, Tuple[str, Callable]]:
    pre = f"model.layers.{i}.block_sparse_moe.experts.{e}."
    # Mixtral: w1=gate, w3=up, w2=down (reference mixtral container mapping)
    return {pre + "w1.weight": ("w_gate", _t),
            pre + "w3.weight": ("w_up", _t),
            pre + "w2.weight": ("w_down", _t)}


# ------------------------------------------------------------------- loading
def _put(leaf: np.ndarray, sharding, dtype) -> jax.Array:
    if dtype is not None and jnp.issubdtype(leaf.dtype, jnp.floating):
        leaf = leaf.astype(dtype)
    if sharding is not None:
        return jax.device_put(jnp.asarray(leaf), sharding)
    return jnp.asarray(leaf)


def load_hf_checkpoint(path: str,
                       model: Any = None,
                       dtype: Any = None,
                       shardings: Any = None,
                       config_overrides: Optional[Dict[str, Any]] = None,
                       ) -> Tuple[Any, Any]:
    """Load an HF-format checkpoint directory into ``(CausalLM, params)``.

    ``model``: an existing :class:`models.CausalLM` to load into (its config
    must match the checkpoint); default builds one from ``config.json``.
    ``dtype``: cast floating leaves (e.g. ``jnp.bfloat16`` for serving);
    ``None`` keeps the checkpoint's dtypes.
    ``shardings``: optional pytree of ``NamedSharding`` matching the model's
    params — each leaf is ``device_put`` against it as soon as it is
    assembled (TP/fsdp-aware placement without ever holding the whole model
    on host). Build it with ``runtime/zero.tree_param_shardings`` or reuse
    ``Engine.param_shardings`` / ``InferenceEngine.param_shardings``.
    """
    from ..models.transformer import CausalLM

    cfg_path = os.path.join(path, "config.json")
    hf_cfg: Dict[str, Any] = {}
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            hf_cfg = json.load(f)
    if model is None:
        if not hf_cfg:
            raise FileNotFoundError(f"no config.json under {path} and no "
                                    f"model was provided")
        cfg = config_from_hf(hf_cfg, **(config_overrides or {}))
        model = CausalLM(cfg)
    cfg = model.config
    model.hf_config = hf_cfg

    mt = hf_cfg.get("model_type", "llama")
    if mt not in FAMILIES:
        logger.warning(f"model_type {mt!r} unknown — using the llama-family "
                       f"name map")
        mt = "llama"
    top_map_fn, layer_map_fn = FAMILIES[mt](cfg)

    src = HFCheckpointSource(path)
    shard_leaves: Dict[str, Any] = {}
    if shardings is not None:
        flat, _ = jax.tree_util.tree_flatten_with_path(
            shardings, is_leaf=lambda x: hasattr(x, "spec"))
        for kp, s in flat:
            shard_leaves["/".join(_key_str(k) for k in kp)] = s

    def sharding_for(*segs) -> Any:
        return shard_leaves.get("/".join(segs))

    params: Dict[str, Any] = {}

    def emit_into(tree, segs, val):
        d = tree
        for s in segs[:-1]:
            d = d.setdefault(s, {})
        d[segs[-1]] = val

    # ---- top-level leaves
    for segs, (name, fn) in top_map_fn().items():
        if segs == ("lm_head", "kernel") and name not in src:
            # tied on disk but untied config: reuse the embedding
            emb_name = top_map_fn()[("embed", "embedding")][0]
            arr = _t(src.get(emb_name))
        else:
            arr = fn(src.get(name))
        emit_into(params, segs, _put(arr, sharding_for(*segs), dtype))

    # ---- per-layer leaves, assembled stacked (scan) or as a list.
    # models/transformer.py applies MoE uniformly when cfg.any_moe (scan
    # requires homogeneous layers), so the map mirrors that.
    def assemble_stacked() -> Dict[str, Any]:
        """One stacked leaf at a time: fill its [L, ...] host buffer across
        layers, device_put, free — peak host memory is one leaf, never the
        model (shards are random-access, so per-leaf sweeps cost no extra
        I/O passes through any one file region)."""
        L = cfg.num_layers
        out: Dict[str, Any] = {}
        layer0 = layer_map_fn(0)
        for segs, (name0, fn0) in layer0.items():
            p0 = fn0(src.get(name0))
            buf = np.empty((L,) + p0.shape, p0.dtype)
            buf[0] = p0
            for i in range(1, L):
                name_i, fn_i = layer_map_fn(i)[segs]
                buf[i] = fn_i(src.get(name_i))
            emit_into(out, segs, _put(buf, sharding_for("layers", *segs),
                                      dtype))
            del buf
        if cfg.any_moe:
            E = cfg.num_experts
            for key in ("w_gate", "w_up", "w_down"):
                buf = None
                for i in range(L):
                    for e in range(E):
                        name, (_, fn) = next(
                            (n, v) for n, v in _expert_names(i, e).items()
                            if v[0] == key)
                        p = fn(src.get(name))
                        if buf is None:
                            buf = np.empty((L, E) + p.shape, p.dtype)
                        buf[i, e] = p
                emit_into(out, ("moe", key),
                          _put(buf, sharding_for("layers", "moe", key),
                               dtype))
                del buf
        return out

    def assemble_list():
        layers = []
        for i in range(cfg.num_layers):
            lp: Dict[str, Any] = {}
            for segs, (name, fn) in layer_map_fn(i).items():
                emit_into(lp, segs, _put(fn(src.get(name)),
                                         sharding_for("layers", str(i),
                                                      *segs), dtype))
            if cfg.any_moe:
                stacked: Dict[str, list] = {}
                for e in range(cfg.num_experts):
                    for name, (key, fn) in _expert_names(i, e).items():
                        stacked.setdefault(key, []).append(fn(src.get(name)))
                for key, mats in stacked.items():
                    lp.setdefault("moe", {})[key] = _put(
                        np.stack(mats), sharding_for("layers", str(i), "moe",
                                                     key), dtype)
            layers.append(lp)
        return layers

    params["layers"] = assemble_stacked() if cfg.scan_layers else assemble_list()
    src.close()
    n = sum(int(np.prod(np.shape(p)))
            for p in jax.tree_util.tree_leaves(params))
    log_dist(f"loaded HF checkpoint {path} ({mt}): {n/1e6:.1f}M params "
             f"({'safetensors' if src._use_safetensors else 'torch bins'})")
    return model, params


# ======================================================================
# Encoder families: BERT / DistilBERT (reference containers/bert.py,
# distil_bert.py) and CLIP (containers/clip.py)
# ======================================================================
def encoder_config_from_hf(hf: Dict[str, Any], **overrides):
    """HF ``config.json`` → :class:`models.encoder.EncoderConfig`."""
    from ..models.encoder import EncoderConfig

    mt = hf.get("model_type", "bert")
    if mt == "bert":
        kw = dict(vocab_size=hf.get("vocab_size", 30522),
                  hidden_size=hf.get("hidden_size", 768),
                  intermediate_size=hf.get("intermediate_size", 3072),
                  num_layers=hf.get("num_hidden_layers", 12),
                  num_heads=hf.get("num_attention_heads", 12),
                  max_seq_len=hf.get("max_position_embeddings", 512),
                  type_vocab_size=hf.get("type_vocab_size", 2),
                  layer_norm_eps=float(hf.get("layer_norm_eps", 1e-12)),
                  activation=_map_activation(hf.get("hidden_act", "gelu")))
    elif mt == "distilbert":
        kw = dict(vocab_size=hf.get("vocab_size", 30522),
                  hidden_size=hf.get("dim", 768),
                  intermediate_size=hf.get("hidden_dim", 3072),
                  num_layers=hf.get("n_layers", 6),
                  num_heads=hf.get("n_heads", 12),
                  max_seq_len=hf.get("max_position_embeddings", 512),
                  type_vocab_size=0,
                  layer_norm_eps=1e-12,
                  activation=_map_activation(hf.get("activation", "gelu")))
    else:
        raise ValueError(f"not an encoder model_type: {mt!r}")
    kw.update(overrides)
    return EncoderConfig(**kw)


def _bert_maps(cfg):
    top = {
        ("embed", "word"): ("bert.embeddings.word_embeddings.weight", _id),
        ("embed", "pos"): ("bert.embeddings.position_embeddings.weight", _id),
        ("embed", "type"): ("bert.embeddings.token_type_embeddings.weight",
                            _id),
        ("embed_norm", "scale"): ("bert.embeddings.LayerNorm.weight", _id),
        ("embed_norm", "bias"): ("bert.embeddings.LayerNorm.bias", _id),
        ("mlm", "dense"): ("cls.predictions.transform.dense.weight", _t),
        ("mlm", "bias_d"): ("cls.predictions.transform.dense.bias", _id),
        ("mlm", "norm", "scale"):
            ("cls.predictions.transform.LayerNorm.weight", _id),
        ("mlm", "norm", "bias"):
            ("cls.predictions.transform.LayerNorm.bias", _id),
        ("mlm", "decoder_bias"): ("cls.predictions.bias", _id),
        ("pooler", "w"): ("bert.pooler.dense.weight", _t),
        ("pooler", "b"): ("bert.pooler.dense.bias", _id),
    }

    def layer(i):
        b = f"bert.encoder.layer.{i}."
        return {
            ("attn", "wq"): (b + "attention.self.query.weight", _t),
            ("attn", "bq"): (b + "attention.self.query.bias", _id),
            ("attn", "wk"): (b + "attention.self.key.weight", _t),
            ("attn", "bk"): (b + "attention.self.key.bias", _id),
            ("attn", "wv"): (b + "attention.self.value.weight", _t),
            ("attn", "bv"): (b + "attention.self.value.bias", _id),
            ("attn", "wo"): (b + "attention.output.dense.weight", _t),
            ("attn", "bo"): (b + "attention.output.dense.bias", _id),
            ("attn_norm", "scale"):
                (b + "attention.output.LayerNorm.weight", _id),
            ("attn_norm", "bias"):
                (b + "attention.output.LayerNorm.bias", _id),
            ("mlp", "fc1"): (b + "intermediate.dense.weight", _t),
            ("mlp", "b1"): (b + "intermediate.dense.bias", _id),
            ("mlp", "fc2"): (b + "output.dense.weight", _t),
            ("mlp", "b2"): (b + "output.dense.bias", _id),
            ("mlp_norm", "scale"): (b + "output.LayerNorm.weight", _id),
            ("mlp_norm", "bias"): (b + "output.LayerNorm.bias", _id),
        }

    return top, layer


def _distilbert_maps(cfg):
    top = {
        ("embed", "word"):
            ("distilbert.embeddings.word_embeddings.weight", _id),
        ("embed", "pos"):
            ("distilbert.embeddings.position_embeddings.weight", _id),
        ("embed_norm", "scale"): ("distilbert.embeddings.LayerNorm.weight",
                                  _id),
        ("embed_norm", "bias"): ("distilbert.embeddings.LayerNorm.bias",
                                 _id),
        ("mlm", "dense"): ("vocab_transform.weight", _t),
        ("mlm", "bias_d"): ("vocab_transform.bias", _id),
        ("mlm", "norm", "scale"): ("vocab_layer_norm.weight", _id),
        ("mlm", "norm", "bias"): ("vocab_layer_norm.bias", _id),
        ("mlm", "decoder"): ("vocab_projector.weight", _t),
        ("mlm", "decoder_bias"): ("vocab_projector.bias", _id),
    }

    def layer(i):
        b = f"distilbert.transformer.layer.{i}."
        return {
            ("attn", "wq"): (b + "attention.q_lin.weight", _t),
            ("attn", "bq"): (b + "attention.q_lin.bias", _id),
            ("attn", "wk"): (b + "attention.k_lin.weight", _t),
            ("attn", "bk"): (b + "attention.k_lin.bias", _id),
            ("attn", "wv"): (b + "attention.v_lin.weight", _t),
            ("attn", "bv"): (b + "attention.v_lin.bias", _id),
            ("attn", "wo"): (b + "attention.out_lin.weight", _t),
            ("attn", "bo"): (b + "attention.out_lin.bias", _id),
            ("attn_norm", "scale"): (b + "sa_layer_norm.weight", _id),
            ("attn_norm", "bias"): (b + "sa_layer_norm.bias", _id),
            ("mlp", "fc1"): (b + "ffn.lin1.weight", _t),
            ("mlp", "b1"): (b + "ffn.lin1.bias", _id),
            ("mlp", "fc2"): (b + "ffn.lin2.weight", _t),
            ("mlp", "b2"): (b + "ffn.lin2.bias", _id),
            ("mlp_norm", "scale"): (b + "output_layer_norm.weight", _id),
            ("mlp_norm", "bias"): (b + "output_layer_norm.bias", _id),
        }

    return top, layer


def load_hf_encoder_checkpoint(path: str, dtype: Any = None,
                               config_overrides: Optional[Dict] = None):
    """Load an HF BERT/DistilBERT checkpoint → ``(BertModel, params)``.

    Optional pieces absent from the export (pooler on MaskedLM saves, the
    MLM head on encoder-only saves) keep their random init with a warning
    — matching HF's "some weights were newly initialized" behavior.
    """
    from ..models.encoder import BertModel

    with open(os.path.join(path, "config.json")) as f:
        hf_cfg = json.load(f)
    mt = hf_cfg.get("model_type", "bert")
    cfg = encoder_config_from_hf(hf_cfg, **(config_overrides or {}))
    src = HFCheckpointSource(path)
    if mt == "distilbert":
        # vocab_projector is tied to the word embeddings by default, and
        # safetensors omits the shared tensor — tie when it's absent
        tie = "vocab_projector.weight" not in src
        model = BertModel(cfg, tie_mlm_decoder=tie)
        top, layer = _distilbert_maps(cfg)
        if tie:
            top = {k: v for k, v in top.items() if k != ("mlm", "decoder")}
    else:
        # an untied MLM decoder ships as its own tensor; tied exports omit
        # it (safetensors refuses shared tensors)
        tie = "cls.predictions.decoder.weight" not in src
        model = BertModel(cfg, tie_mlm_decoder=tie)
        top, layer = _bert_maps(cfg)
        if not tie:
            top[("mlm", "decoder")] = ("cls.predictions.decoder.weight", _t)
    model.hf_config = hf_cfg
    params = model.init_params()

    def emit(tree, segs, val):
        d = tree
        for s in segs[:-1]:
            d = d[s]
        d[segs[-1]] = val

    params = jax.tree_util.tree_map(np.asarray, params)  # mutable host tree
    missing = []
    for segs, (name, fn) in top.items():
        if segs == ("embed", "type") and cfg.type_vocab_size == 0:
            continue
        if name in src:
            emit(params, segs, fn(src.get(name)))
        else:
            missing.append(name)
    for i in range(cfg.num_layers):
        for segs, (name, fn) in layer(i).items():
            arr = fn(src.get(name))
            leaf = params["layers"]
            for s in segs[:-1]:
                leaf = leaf[s]
            if i == 0:
                leaf[segs[-1]] = np.empty((cfg.num_layers,) + arr.shape,
                                          arr.dtype)
            leaf[segs[-1]][i] = arr
    # heads the model owns but the family's map never references at all
    # (e.g. BertModel's pooler on a DistilBERT export, which has no pooler):
    # they would otherwise keep random init with no warning and pooled()
    # would silently return garbage
    mapped_roots = {segs[0] for segs in top} | {"layers"}
    unmapped = [k for k in params if k not in mapped_roots]
    if missing or unmapped:
        logger.warning("encoder checkpoint %s: %d heads kept at random "
                       "init (absent from export): %s%s", path,
                       len(missing) + len(unmapped), missing[:4],
                       f"; unmapped for {mt}: {unmapped}" if unmapped else "")
    if dtype is not None:
        params = jax.tree_util.tree_map(
            lambda x: x.astype(dtype)
            if jnp.issubdtype(np.asarray(x).dtype, jnp.floating) else x,
            params)
    src.close()
    log_dist(f"loaded HF encoder checkpoint {path} ({mt})")
    return model, params


def load_hf_clip_checkpoint(path: str, dtype: Any = None):
    """Load an HF CLIPModel checkpoint → ``(CLIPModel, params)``
    (reference ``module_inject/containers/clip.py`` parity surface)."""
    from ..models.encoder import CLIPConfig, CLIPModel, EncoderConfig

    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    tc, vc = hf["text_config"], hf["vision_config"]
    cfg = CLIPConfig(
        text=EncoderConfig(
            vocab_size=tc.get("vocab_size", 49408),
            hidden_size=tc.get("hidden_size", 512),
            intermediate_size=tc.get("intermediate_size", 2048),
            num_layers=tc.get("num_hidden_layers", 12),
            num_heads=tc.get("num_attention_heads", 8),
            max_seq_len=tc.get("max_position_embeddings", 77),
            type_vocab_size=0,
            layer_norm_eps=float(tc.get("layer_norm_eps", 1e-5)),
            activation=("quick_gelu" if tc.get("hidden_act", "quick_gelu")
                        == "quick_gelu" else
                        _map_activation(tc["hidden_act"])),
            norm_position="pre", causal=True),
        vision=EncoderConfig(
            vocab_size=0,
            hidden_size=vc.get("hidden_size", 768),
            intermediate_size=vc.get("intermediate_size", 3072),
            num_layers=vc.get("num_hidden_layers", 12),
            num_heads=vc.get("num_attention_heads", 12),
            type_vocab_size=0,
            layer_norm_eps=float(vc.get("layer_norm_eps", 1e-5)),
            activation=("quick_gelu" if vc.get("hidden_act", "quick_gelu")
                        == "quick_gelu" else
                        _map_activation(vc["hidden_act"])),
            norm_position="pre",
            image_size=vc.get("image_size", 224),
            patch_size=vc.get("patch_size", 32)),
        projection_dim=hf.get("projection_dim", 512),
        eos_token_id=tc.get("eos_token_id", hf.get("eos_token_id", 49407)))
    model = CLIPModel(cfg)
    model.hf_config = hf
    src = HFCheckpointSource(path)
    params = jax.tree_util.tree_map(np.asarray, model.init_params())

    def tower_layers(prefix, tcfg, dest):
        for i in range(tcfg.num_layers):
            b = f"{prefix}.encoder.layers.{i}."
            for segs, (name, fn) in {
                ("attn", "wq"): (b + "self_attn.q_proj.weight", _t),
                ("attn", "bq"): (b + "self_attn.q_proj.bias", _id),
                ("attn", "wk"): (b + "self_attn.k_proj.weight", _t),
                ("attn", "bk"): (b + "self_attn.k_proj.bias", _id),
                ("attn", "wv"): (b + "self_attn.v_proj.weight", _t),
                ("attn", "bv"): (b + "self_attn.v_proj.bias", _id),
                ("attn", "wo"): (b + "self_attn.out_proj.weight", _t),
                ("attn", "bo"): (b + "self_attn.out_proj.bias", _id),
                ("attn_norm", "scale"): (b + "layer_norm1.weight", _id),
                ("attn_norm", "bias"): (b + "layer_norm1.bias", _id),
                ("mlp", "fc1"): (b + "mlp.fc1.weight", _t),
                ("mlp", "b1"): (b + "mlp.fc1.bias", _id),
                ("mlp", "fc2"): (b + "mlp.fc2.weight", _t),
                ("mlp", "b2"): (b + "mlp.fc2.bias", _id),
                ("mlp_norm", "scale"): (b + "layer_norm2.weight", _id),
                ("mlp_norm", "bias"): (b + "layer_norm2.bias", _id),
            }.items():
                arr = fn(src.get(name))
                leaf = dest
                for s in segs[:-1]:
                    leaf = leaf[s]
                if i == 0:
                    leaf[segs[-1]] = np.empty(
                        (tcfg.num_layers,) + arr.shape, arr.dtype)
                leaf[segs[-1]][i] = arr

    t = params["text"]
    t["embed"]["word"] = src.get("text_model.embeddings.token_embedding.weight")
    t["embed"]["pos"] = src.get(
        "text_model.embeddings.position_embedding.weight")
    tower_layers("text_model", cfg.text, t["layers"])
    t["final_norm"]["scale"] = src.get("text_model.final_layer_norm.weight")
    t["final_norm"]["bias"] = src.get("text_model.final_layer_norm.bias")

    v = params["vision"]
    v["class_embed"] = src.get("vision_model.embeddings.class_embedding")
    pw = src.get("vision_model.embeddings.patch_embedding.weight")
    # torch conv [D, 3, p, p] → matmul [(p·p·3), D] in (ph, pw, c) order
    v["patch_embed"] = np.transpose(pw, (2, 3, 1, 0)).reshape(-1, pw.shape[0])
    v["pos_embed"] = src.get(
        "vision_model.embeddings.position_embedding.weight")
    # sic: HF ships this layer as "pre_layrnorm"
    v["pre_norm"]["scale"] = src.get("vision_model.pre_layrnorm.weight")
    v["pre_norm"]["bias"] = src.get("vision_model.pre_layrnorm.bias")
    tower_layers("vision_model", cfg.vision, v["layers"])
    v["post_norm"]["scale"] = src.get("vision_model.post_layernorm.weight")
    v["post_norm"]["bias"] = src.get("vision_model.post_layernorm.bias")

    params["text_projection"] = _t(src.get("text_projection.weight"))
    params["visual_projection"] = _t(src.get("visual_projection.weight"))
    params["logit_scale"] = src.get("logit_scale")
    if dtype is not None:
        params = jax.tree_util.tree_map(
            lambda x: x.astype(dtype)
            if jnp.issubdtype(np.asarray(x).dtype, jnp.floating) else x,
            params)
    src.close()
    log_dist(f"loaded HF CLIP checkpoint {path}")
    return model, params


# ======================================================================
# Megatron-LM GPT checkpoints (reference containers/megatron_gpt.py —
# fused per-head query_key_value, megatron_v2 layout)
# ======================================================================
def load_megatron_checkpoint(path: str, num_heads: int, dtype: Any = None,
                             config_overrides: Optional[Dict] = None):
    """Load a Megatron-LM GPT checkpoint (``model_optim_rng.pt``-style
    torch state dict) into ``(CausalLM, params)``.

    Reference analog: ``module_inject/containers/megatron_gpt.py``
    (MegatronLayerPolicy over ``ParallelTransformerLayer``: fused
    ``query_key_value`` [3·d, d] in the per-head megatron-v2 layout —
    decoded by the same ``_fused3`` helper BLOOM/NeoX use — ``dense``,
    ``mlp.dense_h_to_4h`` / ``dense_4h_to_h``, input/post_attention
    layernorms). ``num_heads`` cannot be inferred from shapes and must be
    supplied (megatron args carry it out of band). ``dtype`` casts
    floating leaves during assembly; ``config_overrides`` reach
    :class:`ModelConfig` (e.g. ``{"dtype": "float32"}`` for the compute
    dtype, ``{"activation": "gelu"}`` for tanh-gelu checkpoints). Handles learned-absolute OR rotary
    positions and tied OR untied (``output_layer``) unembeddings.
    """
    import torch

    from ..models.transformer import CausalLM

    sd = torch.load(path, map_location="cpu", weights_only=False)
    sd = sd.get("model", sd)
    lm = sd.get("language_model", sd)
    emb = lm["embedding"]
    enc = lm.get("encoder", lm.get("transformer"))
    if enc is None:
        raise ValueError("no encoder/transformer section in checkpoint")

    def npy(t):
        t = t.float() if t.dtype == torch.bfloat16 else t
        a = t.numpy()
        if dtype is not None and jnp.issubdtype(a.dtype, jnp.floating):
            a = a.astype(dtype)
        return a

    word = npy(emb["word_embeddings"]["weight"])
    pos = (npy(emb["position_embeddings"]["weight"])
           if "position_embeddings" in emb else None)
    untied = lm.get("output_layer")
    n_layers = 1 + max(int(k.split(".")[1]) for k in enc
                       if k.startswith("layers."))
    d = word.shape[1]
    hd = d // num_heads
    kw = dict(vocab_size=word.shape[0], hidden_size=d,
              intermediate_size=enc[
                  "layers.0.mlp.dense_h_to_4h.weight"].shape[0],
              num_layers=n_layers, num_heads=num_heads,
              tie_embeddings=untied is None,
              norm_type="layernorm",
              pos_embed="learned" if pos is not None else "rope",
              mlp_type="mlp", use_bias=True,
              activation="gelu_exact", rms_norm_eps=1e-5)
    if pos is not None:
        kw["max_seq_len"] = pos.shape[0]
    kw.update(config_overrides or {})
    cfg = ModelConfig(**kw)
    model = CausalLM(cfg)

    def layer_leaves(i):
        pre = f"layers.{i}."
        att = (pre + "self_attention."
               if pre + "self_attention.query_key_value.weight" in enc
               else pre + "attention.")
        qkv_w = npy(enc[att + "query_key_value.weight"])
        qkv_b = npy(enc[att + "query_key_value.bias"])
        leaves = {
            "attn": {"wq": _fused3(0, num_heads, hd)(qkv_w),
                     "wk": _fused3(1, num_heads, hd)(qkv_w),
                     "wv": _fused3(2, num_heads, hd)(qkv_w),
                     "bq": _fused3(0, num_heads, hd)(qkv_b),
                     "bk": _fused3(1, num_heads, hd)(qkv_b),
                     "bv": _fused3(2, num_heads, hd)(qkv_b),
                     "wo": _t(npy(enc[att + "dense.weight"])),
                     "bo": npy(enc[att + "dense.bias"])},
            "attn_norm": {"scale": npy(enc[pre + "input_layernorm.weight"]),
                          "bias": npy(enc[pre + "input_layernorm.bias"])},
            "mlp": {"fc1": _t(npy(enc[pre + "mlp.dense_h_to_4h.weight"])),
                    "b1": npy(enc[pre + "mlp.dense_h_to_4h.bias"]),
                    "fc2": _t(npy(enc[pre + "mlp.dense_4h_to_h.weight"])),
                    "b2": npy(enc[pre + "mlp.dense_4h_to_h.bias"])},
            "mlp_norm": {"scale": npy(
                             enc[pre + "post_attention_layernorm.weight"]),
                         "bias": npy(
                             enc[pre + "post_attention_layernorm.bias"])},
        }
        return leaves

    per_layer = [layer_leaves(i) for i in range(n_layers)]
    if cfg.scan_layers:
        layers: Any = jax.tree_util.tree_map(lambda *ls: np.stack(ls),
                                             *per_layer)
    else:
        layers = per_layer
    params = {
        "embed": {"embedding": word},
        "layers": layers,
        "final_norm": {"scale": npy(enc["final_layernorm.weight"]),
                       "bias": npy(enc["final_layernorm.bias"])},
    }
    if pos is not None:
        params["pos_embed"] = {"embedding": pos}
    if untied is not None:
        params["lm_head"] = {"kernel": _t(npy(untied["weight"]))}
    log_dist(f"loaded Megatron-LM checkpoint {path}: {n_layers} layers, "
             f"d={d}, {'tied' if untied is None else 'untied'} unembed")
    return model, params
