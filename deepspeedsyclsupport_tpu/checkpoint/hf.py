"""HuggingFace checkpoint ingestion — serve/train real pretrained weights.

The TPU-native analog of the reference's model-integration stack:

* the 19 per-architecture policies that map HF module trees onto fused
  containers (``deepspeed/module_inject/containers/{llama,llama2,...}.py``,
  ``replace_module.py:182``),
* the v2 checkpoint engines streaming HF shards
  (``deepspeed/inference/v2/checkpoint/huggingface_engine.py:1``), and
* the flat-parameter mapping DSL (``inference/v2/model_implementations/
  layer_container_base.py``, ``flat_model_helpers.py``).

Because the framework owns the model definition (``models/transformer.py``),
"policy" collapses to a *name map*: HF tensor names → pytree paths, with the
orientation transpose (torch ``nn.Linear`` stores ``[out, in]``; our einsum
contracts ``[in, out]``). Streaming discipline: tensors are read one at a time
from safetensors/torch shards, assembled per-leaf (stacked layer leaves are
filled layer by layer), pushed to device against the target sharding, and the
host buffer freed — peak host memory is one stacked leaf, never the model.

Supported families (same set the reference's FastGen serves first-class):
Llama/Llama-2/-3, Mistral, Mixtral (MoE), plus anything config-compatible
(Qwen2-style GQA dense models load through the same map).
"""
import json
import os
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .engine import _key_str
from ..models.config import ModelConfig
from ..utils.logging import log_dist, logger

__all__ = ["config_from_hf", "load_hf_checkpoint", "HFCheckpointSource"]

SAFE_INDEX = "model.safetensors.index.json"
SAFE_SINGLE = "model.safetensors"
BIN_INDEX = "pytorch_model.bin.index.json"
BIN_SINGLE = "pytorch_model.bin"


# --------------------------------------------------------------------- config
def _map_activation(act: str) -> str:
    """HF ``hidden_act`` → our activation. Unknown values raise — silently
    substituting SwiGLU would load cleanly and generate garbage."""
    known = {"silu": "silu", "swish": "silu", "gelu": "gelu",
             # jax.nn.gelu defaults to the tanh approximation, which is what
             # these HF names mean
             "gelu_new": "gelu", "gelu_pytorch_tanh": "gelu"}
    if act not in known:
        raise ValueError(
            f"unsupported hidden_act {act!r} (supported: {sorted(known)})")
    return known[act]


def config_from_hf(hf: Dict[str, Any], **overrides) -> ModelConfig:
    """HF ``config.json`` dict → :class:`ModelConfig` (the per-arch policy's
    config half; reference containers read the same fields off HF configs)."""
    kw = dict(
        vocab_size=hf.get("vocab_size", 32000),
        hidden_size=hf.get("hidden_size", 4096),
        intermediate_size=hf.get("intermediate_size", 11008),
        num_layers=hf.get("num_hidden_layers", 32),
        num_heads=hf.get("num_attention_heads", 32),
        num_kv_heads=hf.get("num_key_value_heads",
                            hf.get("num_attention_heads", 32)),
        head_dim=hf.get("head_dim"),
        max_seq_len=hf.get("max_position_embeddings", 4096),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
        activation=_map_activation(hf.get("hidden_act", "silu")),
    )
    if hf.get("model_type") == "mixtral" or "num_local_experts" in hf:
        kw.update(num_experts=hf.get("num_local_experts", 8),
                  num_experts_per_tok=hf.get("num_experts_per_tok", 2),
                  aux_loss_coef=float(hf.get("router_aux_loss_coef", 0.01)))
    kw.update(overrides)
    return ModelConfig(**kw)


# --------------------------------------------------------------------- source
class HFCheckpointSource:
    """Random access to the tensors of an HF checkpoint directory, reading
    lazily from safetensors (preferred) or torch ``.bin`` shards (the two
    layouts ``huggingface_engine.py`` handles)."""

    def __init__(self, path: str):
        self.path = path
        self._name_to_file: Dict[str, str] = {}
        self._safe_handles: Dict[str, Any] = {}
        self._bin_cache: Dict[str, Dict[str, Any]] = {}
        self._use_safetensors = True
        if os.path.exists(os.path.join(path, SAFE_INDEX)):
            with open(os.path.join(path, SAFE_INDEX)) as f:
                self._name_to_file = dict(json.load(f)["weight_map"])
        elif os.path.exists(os.path.join(path, SAFE_SINGLE)):
            from safetensors import safe_open

            with safe_open(os.path.join(path, SAFE_SINGLE),
                           framework="numpy") as f:
                self._name_to_file = {k: SAFE_SINGLE for k in f.keys()}
        elif os.path.exists(os.path.join(path, BIN_INDEX)):
            self._use_safetensors = False
            with open(os.path.join(path, BIN_INDEX)) as f:
                self._name_to_file = dict(json.load(f)["weight_map"])
        elif os.path.exists(os.path.join(path, BIN_SINGLE)):
            self._use_safetensors = False
            sd = self._load_bin(BIN_SINGLE)
            self._name_to_file = {k: BIN_SINGLE for k in sd}
        else:
            raise FileNotFoundError(
                f"no model.safetensors[.index.json] or pytorch_model.bin"
                f"[.index.json] under {path}")

    @property
    def names(self) -> Iterable[str]:
        return self._name_to_file.keys()

    def __contains__(self, name: str) -> bool:
        return name in self._name_to_file

    def _load_bin(self, fname: str) -> Dict[str, Any]:
        if fname not in self._bin_cache:
            import torch

            self._bin_cache[fname] = torch.load(
                os.path.join(self.path, fname), map_location="cpu",
                weights_only=True)
        return self._bin_cache[fname]

    def get(self, name: str) -> np.ndarray:
        """One tensor as numpy (bf16 arrives as ml_dtypes.bfloat16)."""
        fname = self._name_to_file[name]
        if self._use_safetensors:
            if fname not in self._safe_handles:
                from safetensors import safe_open

                self._safe_handles[fname] = safe_open(
                    os.path.join(self.path, fname), framework="numpy")
            return self._safe_handles[fname].get_tensor(name)
        t = self._load_bin(fname)[name]
        if str(t.dtype) == "torch.bfloat16":
            import ml_dtypes

            # torch has no numpy bridge for bf16: round-trip through fp32
            return t.float().numpy().astype(ml_dtypes.bfloat16)
        return t.numpy()

    def close(self):
        self._safe_handles.clear()
        self._bin_cache.clear()


# ----------------------------------------------------------------- name map
def _hf_layer_map(i: int, moe: bool) -> Dict[str, Tuple[Tuple[str, ...], bool]]:
    """HF name → (pytree path under the layer, transpose?) for layer ``i``."""
    pre = f"model.layers.{i}."
    m = {
        pre + "input_layernorm.weight": (("attn_norm", "scale"), False),
        pre + "self_attn.q_proj.weight": (("attn", "wq"), True),
        pre + "self_attn.k_proj.weight": (("attn", "wk"), True),
        pre + "self_attn.v_proj.weight": (("attn", "wv"), True),
        pre + "self_attn.o_proj.weight": (("attn", "wo"), True),
        pre + "post_attention_layernorm.weight": (("mlp_norm", "scale"), False),
    }
    if moe:
        m[pre + "block_sparse_moe.gate.weight"] = (("moe", "router"), True)
        # expert weights handled specially (stacked over the expert dim)
    else:
        m[pre + "mlp.gate_proj.weight"] = (("mlp", "w_gate"), True)
        m[pre + "mlp.up_proj.weight"] = (("mlp", "w_up"), True)
        m[pre + "mlp.down_proj.weight"] = (("mlp", "w_down"), True)
    return m


def _expert_names(i: int, e: int) -> Dict[str, Tuple[str, bool]]:
    pre = f"model.layers.{i}.block_sparse_moe.experts.{e}."
    # Mixtral: w1=gate, w3=up, w2=down (reference mixtral container mapping)
    return {pre + "w1.weight": ("w_gate", True),
            pre + "w3.weight": ("w_up", True),
            pre + "w2.weight": ("w_down", True)}


# ------------------------------------------------------------------- loading
def _put(leaf: np.ndarray, sharding, dtype) -> jax.Array:
    if dtype is not None and jnp.issubdtype(leaf.dtype, jnp.floating):
        leaf = leaf.astype(dtype)
    if sharding is not None:
        return jax.device_put(jnp.asarray(leaf), sharding)
    return jnp.asarray(leaf)


def load_hf_checkpoint(path: str,
                       model: Any = None,
                       dtype: Any = None,
                       shardings: Any = None,
                       config_overrides: Optional[Dict[str, Any]] = None,
                       ) -> Tuple[Any, Any]:
    """Load an HF-format checkpoint directory into ``(CausalLM, params)``.

    ``model``: an existing :class:`models.CausalLM` to load into (its config
    must match the checkpoint); default builds one from ``config.json``.
    ``dtype``: cast floating leaves (e.g. ``jnp.bfloat16`` for serving);
    ``None`` keeps the checkpoint's dtypes.
    ``shardings``: optional pytree of ``NamedSharding`` matching the model's
    params — each leaf is ``device_put`` against it as soon as it is
    assembled (TP/fsdp-aware placement without ever holding the whole model
    on host). Build it with ``runtime/zero.tree_param_shardings`` or reuse
    ``Engine.param_shardings`` / ``InferenceEngine.param_shardings``.
    """
    from ..models.transformer import CausalLM

    cfg_path = os.path.join(path, "config.json")
    hf_cfg: Dict[str, Any] = {}
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            hf_cfg = json.load(f)
    if model is None:
        if not hf_cfg:
            raise FileNotFoundError(f"no config.json under {path} and no "
                                    f"model was provided")
        cfg = config_from_hf(hf_cfg, **(config_overrides or {}))
        model = CausalLM(cfg)
    cfg = model.config
    model.hf_config = hf_cfg

    src = HFCheckpointSource(path)
    shard_leaves: Dict[str, Any] = {}
    if shardings is not None:
        flat, _ = jax.tree_util.tree_flatten_with_path(
            shardings, is_leaf=lambda x: hasattr(x, "spec"))
        for kp, s in flat:
            shard_leaves["/".join(_key_str(k) for k in kp)] = s

    def sharding_for(*segs) -> Any:
        return shard_leaves.get("/".join(segs))

    def fetch(name: str, transpose: bool) -> np.ndarray:
        arr = src.get(name)
        return np.ascontiguousarray(arr.T) if transpose else arr

    params: Dict[str, Any] = {}
    # ---- top-level leaves
    params["embed"] = {"embedding": _put(
        fetch("model.embed_tokens.weight", False),
        sharding_for("embed", "embedding"), dtype)}
    params["final_norm"] = {"scale": _put(
        fetch("model.norm.weight", False),
        sharding_for("final_norm", "scale"), dtype)}
    if not cfg.tie_embeddings:
        if "lm_head.weight" in src:
            head = fetch("lm_head.weight", True)
        else:  # tied on disk but untied config: reuse the embedding
            head = np.ascontiguousarray(
                src.get("model.embed_tokens.weight").T)
        params["lm_head"] = {"kernel": _put(
            head, sharding_for("lm_head", "kernel"), dtype)}

    # ---- per-layer leaves, assembled stacked (scan) or as a list.
    # models/transformer.py applies MoE uniformly when cfg.any_moe (scan
    # requires homogeneous layers), so the map mirrors that.
    def is_moe_layer(i: int) -> bool:
        return cfg.any_moe

    def assemble_stacked() -> Dict[str, Any]:
        """One stacked leaf at a time: fill its [L, ...] host buffer across
        layers, device_put, free — peak host memory is one leaf, never the
        model (shards are random-access, so per-leaf sweeps cost no extra
        I/O passes through any one file region)."""
        L = cfg.num_layers
        out: Dict[str, Any] = {}

        def emit(segs: Tuple[str, ...], buf: np.ndarray):
            d = out
            for s in segs[:-1]:
                d = d.setdefault(s, {})
            d[segs[-1]] = _put(buf, sharding_for("layers", *segs), dtype)

        # invert the per-layer map: leaf path → per-layer HF name
        layer0 = _hf_layer_map(0, is_moe_layer(0))
        for name0, (segs, tr) in layer0.items():
            p0 = fetch(name0, tr)
            buf = np.empty((L,) + p0.shape, p0.dtype)
            buf[0] = p0
            for i in range(1, L):
                name_i = {n: k for n, (k, _) in
                          _hf_layer_map(i, is_moe_layer(i)).items()}
                hf_name = next(n for n, k in name_i.items() if k == segs)
                buf[i] = fetch(hf_name, tr)
            emit(segs, buf)
            del buf
        if cfg.any_moe:
            E = cfg.num_experts
            for key in ("w_gate", "w_up", "w_down"):
                p0 = None
                buf = None
                for i in range(L):
                    for e in range(E):
                        name, (_, tr) = next(
                            (n, v) for n, v in _expert_names(i, e).items()
                            if v[0] == key)
                        p = fetch(name, tr)
                        if buf is None:
                            buf = np.empty((L, E) + p.shape, p.dtype)
                        buf[i, e] = p
                emit(("moe", key), buf)
                del buf
        return out

    def assemble_list():
        layers = []
        for i in range(cfg.num_layers):
            lp: Dict[str, Any] = {}
            for name, (segs, tr) in _hf_layer_map(i, is_moe_layer(i)).items():
                d = lp
                for s in segs[:-1]:
                    d = d.setdefault(s, {})
                d[segs[-1]] = _put(fetch(name, tr),
                                   sharding_for("layers", str(i), *segs), dtype)
            if is_moe_layer(i):
                stacked: Dict[str, list] = {}
                for e in range(cfg.num_experts):
                    for name, (key, tr) in _expert_names(i, e).items():
                        stacked.setdefault(key, []).append(fetch(name, tr))
                for key, mats in stacked.items():
                    lp.setdefault("moe", {})[key] = _put(
                        np.stack(mats), sharding_for("layers", str(i), "moe",
                                                     key), dtype)
            layers.append(lp)
        return layers

    params["layers"] = assemble_stacked() if cfg.scan_layers else assemble_list()
    src.close()
    n = sum(int(np.prod(np.shape(p)))
            for p in jax.tree_util.tree_leaves(params))
    log_dist(f"loaded HF checkpoint {path}: {n/1e6:.1f}M params "
             f"({'safetensors' if src._use_safetensors else 'torch bins'})")
    return model, params
