"""ZeRO-style memory partitioning via sharding specs.

TPU-native analog of the reference's ZeRO machinery (SURVEY.md §2.4):

* stage 1 — optimizer-state sharding: ``DeepSpeedZeroOptimizer`` with
  ``partition_gradients=False`` (``runtime/zero/stage_1_and_2.py:96``).
* stage 2 — + gradient sharding: IPG buckets + ``average_tensor`` reduce-scatter
  (``stage_1_and_2.py:894,1004``).
* stage 3 — + parameter sharding: ``DeepSpeedZeroOptimizer_Stage3``
  (``stage3.py:73``), param lifecycle hooks (``parameter_offload.py:201``),
  prefetch coordinator (``partitioned_param_coordinator.py:58``).

The reference needs ~8k LoC of hooks, buckets, and streams because torch executes
eagerly: it must *manually* gather params before use, free them after, and overlap
reduce-scatter with backward. Under XLA the same data movement is derived from
placement: declare each tensor's sharding over the ``fsdp`` mesh axis and the SPMD
partitioner inserts the all-gathers (param use), reduce-scatters (grad math), and
overlaps them with compute (what the prefetch coordinator/overlap_comm hand-tune).
What remains our job is the *placement policy* — which tensors shard, over which
axis, along which dimension — plus offload targeting and the numerics ring
(loss scaling, grad clipping, overflow) which lives in ``engine.py``/``loss_scaler.py``.

Semantics map (all stages keep DP gradient averaging):

=======  ==========================  ====================================
stage    sharded state               sharding rule here
0        nothing                     params/opt replicated over fsdp
1        optimizer state             opt moments sharded, params replicated
2        + gradients                 same placement as 1 (XLA reduce-scatters
                                     grads into the sharded update; the explicit
                                     analog of stage-2 bucketing)
3        + parameters                params sharded too (FSDP)
=======  ==========================  ====================================
"""
import math
from typing import Any, Callable, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..comm.topology import MeshTopology
from ..utils.logging import logger

# Params smaller than this stay replicated at stage 3, mirroring the reference's
# ``stage3_param_persistence_threshold`` (small params are cheaper re-used than
# re-gathered; stage3.py keeps them resident for the same reason).
DEFAULT_PERSISTENCE_THRESHOLD = 10_000


def choose_shard_dim(shape: Tuple[int, ...], n_shards: int,
                     threshold: int = DEFAULT_PERSISTENCE_THRESHOLD) -> Optional[int]:
    """Pick the dimension to shard over fsdp: the largest dim divisible by
    ``n_shards``; None if the tensor is too small or nothing divides."""
    if n_shards <= 1:
        return None
    size = math.prod(shape) if shape else 0
    if size < threshold:
        return None
    candidates = [i for i, d in enumerate(shape) if d % n_shards == 0]
    if not candidates:
        return None
    return max(candidates, key=lambda i: shape[i])


def param_sharding(topo: MeshTopology, stage: int,
                   threshold: int = DEFAULT_PERSISTENCE_THRESHOLD,
                   extra_rules: Optional[Callable] = None) -> Callable:
    """Build a ``leaf -> NamedSharding`` function for parameters.

    ``extra_rules(path, shape)`` may return a PartitionSpec to compose tensor
    parallelism (TP specs win on their dims; fsdp takes a remaining dim). Rules may
    name ``fsdp`` explicitly to pin WHICH dim shards at stage 3 (e.g. keeping the
    stacked-layer dim of a scanned model unsharded); below stage 3 those fsdp
    entries are stripped, so one rule set serves all stages.
    """
    mesh = topo.mesh
    n = topo.axis_sizes["fsdp"]

    def strip_axis(s, ax):
        if isinstance(s, tuple):
            t = tuple(a for a in s if a != ax)
            return t if len(t) > 1 else (t[0] if t else None)
        return None if s == ax else s

    def rule(path, leaf) -> NamedSharding:
        shape = np.shape(leaf)
        ruled = extra_rules(path, shape) if extra_rules else None
        tp_spec = list(ruled) if ruled is not None else []
        tp_spec += [None] * (len(shape) - len(tp_spec))
        if stage < 3:
            tp_spec = [strip_axis(s, "fsdp") for s in tp_spec]
        # each dim must divide by the PRODUCT of its named axis sizes; shed axes
        # (fsdp first — TP layout is load-bearing, FSDP is only a memory saving)
        # until it does
        for i, s in enumerate(tp_spec):
            def axes_of(sp):
                return [a for a in (sp if isinstance(sp, tuple) else (sp,)) if a]

            def divides(sp):
                prod = math.prod(topo.axis_sizes.get(a, 1) for a in axes_of(sp))
                return i < len(shape) and shape[i] % max(prod, 1) == 0

            for ax in (["fsdp"] + axes_of(s)):
                if divides(tp_spec[i]):
                    break
                tp_spec[i] = strip_axis(tp_spec[i], ax)
        if stage >= 3 and n > 1:
            used = {ax for s in tp_spec for ax in (s if isinstance(s, tuple) else (s,))
                    if ax}
            if "fsdp" not in used and math.prod(shape or (0,)) >= threshold:
                # shard the largest free divisible dim over fsdp (choose_shard_dim
                # policy restricted to dims the TP spec left free; 1 = taken
                # sentinel, indivisible by n>1 and never the max)
                free_shape = tuple(d if s is None else 1
                                   for d, s in zip(shape, tp_spec))
                i = choose_shard_dim(free_shape, n, threshold=0)
                if i is not None:
                    tp_spec[i] = "fsdp"
        return NamedSharding(mesh, PartitionSpec(*tp_spec))

    return rule


def tree_param_shardings(params, topo: MeshTopology, stage: int,
                         threshold: int = DEFAULT_PERSISTENCE_THRESHOLD,
                         extra_rules: Optional[Callable] = None):
    rule = param_sharding(topo, stage, threshold, extra_rules)
    return jax.tree_util.tree_map_with_path(rule, params)


def tree_optimizer_shardings(opt_state, params, param_shardings, topo: MeshTopology,
                             stage: int,
                             threshold: int = DEFAULT_PERSISTENCE_THRESHOLD):
    """Sharding pytree for optimizer state.

    Moment tensors (same shape as a param) follow: stage>=3 → the param's sharding;
    stage 1/2 → sharded over fsdp on their largest divisible dim even though the
    param is replicated (that IS ZeRO-1/2: master/opt partitions with full params).
    Scalars (step counters, injected hyperparams) replicate.
    """
    mesh = topo.mesh
    n = topo.axis_sizes["fsdp"]

    # Index params by key path → sharding. Optimizer moments (optax ScaleByAdamState
    # .mu/.nu etc.) share the param tree structure, so a moment leaf's key path ends
    # with its param's key path; matching on (path suffix, shape) — not shape alone —
    # keeps two same-shaped params with different TP shardings distinct.
    path_to_sharding = {}
    p_paths = jax.tree_util.tree_flatten_with_path(params)[0]
    s_leaves = jax.tree_util.tree_leaves(
        param_shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
    for (kp, p), s in zip(p_paths, s_leaves):
        path_to_sharding[jax.tree_util.keystr(kp)] = (np.shape(p), s)

    replicated = NamedSharding(mesh, PartitionSpec())

    def rule(kp, leaf):
        shape = np.shape(leaf)
        if not shape:
            return replicated
        param_s = None
        for i in range(len(kp)):
            ent = path_to_sharding.get(jax.tree_util.keystr(kp[i:]))
            if ent is not None and ent[0] == shape:
                param_s = ent[1]
                break
        if stage >= 3 and param_s is not None:
            return param_s
        if stage >= 1:
            # ZeRO-1/2: partition over fsdp even though the param replicates
            # there — but KEEP the param's TP/expert axes: a moment laid out
            # differently from its gradient makes the SPMD partitioner
            # full-rematerialize it every step (seen on MoE w_gate/w_up)
            base = list(param_s.spec) if param_s is not None else []
            base += [None] * (len(shape) - len(base))
            # size gate on the FULL tensor (stage-3 precedent above): the
            # masked free-shape product would under-count TP-sharded moments
            # and silently skip their fsdp partitioning
            if math.prod(shape) >= threshold:
                free = tuple(d if s is None else 1
                             for d, s in zip(shape, base))
                dim = choose_shard_dim(free, n, threshold=0)
                if dim is not None:
                    base[dim] = "fsdp"
            if any(s is not None for s in base):
                return NamedSharding(mesh, PartitionSpec(*base))
        return replicated

    return jax.tree_util.tree_map_with_path(rule, opt_state)


def predict_memory_per_device(n_params: int, fsdp: int, stage: int, *,
                              offload: bool = False,
                              compute_bytes: int = 4,
                              activation_bytes: float = 0.0,
                              remat: bool = False,
                              num_layers: int = 1) -> float:
    """Predicted peak device bytes for one training step — the numeric core
    behind :func:`describe_memory_plan`, used by the autotuner's
    model-based pruning (reference ``autotuning/autotuner.py``
    ``model_based_tuning`` / ``max_train_micro_batch_size``).

    ``activation_bytes``: full no-remat activation footprint for the whole
    stack at this micro-batch; with ``remat`` only ~one layer's worth is
    live at a time (plus the per-layer residual stream checkpoints).
    """
    n = max(fsdp, 1)
    param_factor = n if stage >= 3 and n > 1 else 1
    grad_factor = n if stage >= 2 and n > 1 else 1
    opt_factor = n if stage >= 1 and n > 1 else 1
    if offload:
        # device holds compute-dtype working params; fp32 master + moments
        # live on host. Grads still materialize on device before the pull.
        mem = n_params * compute_bytes / param_factor
        mem += n_params * 4 / grad_factor
    else:
        mem = n_params * 4 / param_factor          # fp32 master
        mem += n_params * 4 / grad_factor          # fp32 grads
        mem += n_params * 8 / opt_factor           # adam moments
        if compute_bytes != 4:
            mem += n_params * compute_bytes / param_factor  # working cast
    if remat:
        layers = max(num_layers, 1)
        # live layer + residual checkpoints — but never predict MORE than
        # the no-remat footprint (shallow/unknown-depth models)
        mem += min(activation_bytes, activation_bytes / layers * 2)
    else:
        mem += activation_bytes
    return mem


def describe_memory_plan(params, topo: MeshTopology, stage: int,
                         offload_device: Optional[str] = None) -> str:
    """Human-readable partition report (reference: ``see_memory_usage`` +
    stage3 partition logging)."""
    n_params = sum(math.prod(np.shape(p)) for p in jax.tree_util.tree_leaves(params))
    n = topo.axis_sizes["fsdp"]
    param_factor = n if stage >= 3 and n > 1 else 1
    grad_factor = n if stage >= 2 and n > 1 else 1
    opt_factor = n if stage >= 1 and n > 1 else 1
    msg = (f"ZeRO stage {stage}: {n_params / 1e6:.1f}M params, fsdp={n}; "
           f"param mem 1/{param_factor}, grad mem 1/{grad_factor}, "
           f"optimizer mem 1/{opt_factor} per device")
    if offload_device == "cpu":
        msg += ("; offload: fp32 master + optimizer state on host CPU, "
                "device holds compute-dtype params only")
    elif offload_device == "nvme":
        msg += ("; offload: fp32 master on host, optimizer state swapped to "
                "NVMe between steps, device holds compute-dtype params only")
    return msg
