"""Bucketed offload pipeline primitives (ZeRO-Infinity style).

The bandwidth-centric pieces of the hierarchical offload engine
(``runtime/multihost_offload.py``), factored out so they are testable
without devices:

* :func:`plan_buckets` — partition the shard work-list into size-targeted
  buckets, coalescing small leaves (the reference's contiguous swap
  buffers, ``deepspeed/runtime/swap_tensor/optimizer_utils.py`` — transfer
  granularity is a buffer, never a tensor, so tiny leaves don't serialize
  the pipeline on per-request latency).
* :class:`OffloadStats` — per-step byte/seconds ledger for every tier
  (D2H grad pull, host compute, H2D master push, NVMe moment window) with
  the *exposed* stall separated from total transfer occupancy; overlap
  efficiency = 1 − exposed/total is the bench headline.
* :class:`ShardPull` — one async device→host grad-shard fetch
  (non-blocking ``jax.device_put`` to the host backend with a delayed
  wait) so every pull is in flight before anything blocks on it.
* :class:`MomentWindow` — a bounded double-buffered prefetch window of B
  buckets over :class:`~.swap_tensor.AsyncTensorSwapper`: moments are
  prefetched ahead of use, written back behind the compute, and the host
  copies dropped on retirement — host RAM high-water is bounded by the
  window, not the model (``ZeRO-Infinity`` §5; the old path prefetched
  the entire store up front).

Threading contract: worker threads touch numpy only; every jax call
(device_put, np.asarray of a jax array) stays on the caller's thread.
"""
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["BucketItem", "Bucket", "plan_buckets", "OffloadStats",
           "ShardPull", "MomentWindow", "overlap_efficiency",
           "DEFAULT_BUCKET_BYTES"]

#: default size-targeted bucket (coalesced small leaves) — the ONE
#: definition; ``OffloadConfig.bucket_size`` and the pipeline engine both
#: reference it.
DEFAULT_BUCKET_BYTES = 32 * 2 ** 20


def overlap_efficiency(stall_s: float, transfer_s: float) -> float:
    """1 − exposed/total transfer time, clamped to [0, 1] — THE canonical
    definition, shared by the per-step stats, the run summary and the
    Offload/* events (``tools/trace_report.py`` mirrors it inline: the
    offline tool loads no package modules). 1.0 means every byte moved
    entirely under compute; 0 means fully serial; no transfers counts as
    perfectly overlapped."""
    if transfer_s <= 0.0:
        return 1.0
    return min(1.0, max(0.0, 1.0 - stall_s / transfer_s))

#: (leaf_index, shard_key, nbytes) — one logical shard of one pytree leaf.
BucketItem = Tuple[int, str, int]


@dataclass(frozen=True)
class Bucket:
    """One pipeline unit: a contiguous run of shard items whose combined
    size targets the configured bucket bytes."""
    index: int
    items: Tuple[BucketItem, ...]
    nbytes: int


def plan_buckets(items: Sequence[BucketItem],
                 target_bytes: int) -> List[Bucket]:
    """Greedy size-targeted coalescing in leaf order (leaf order is the
    H2D first-use order). Small items pack together until the target is
    reached; an item at least as large as the target gets its own bucket
    (leaves are never split — shard granularity is the transfer unit)."""
    target_bytes = max(1, int(target_bytes))
    buckets: List[Bucket] = []
    cur: List[BucketItem] = []
    cur_bytes = 0

    def flush():
        nonlocal cur, cur_bytes
        if cur:
            buckets.append(Bucket(len(buckets), tuple(cur), cur_bytes))
            cur, cur_bytes = [], 0

    for item in items:
        nbytes = int(item[2])
        if cur_bytes and cur_bytes + nbytes > target_bytes:
            flush()
        cur.append(item)
        cur_bytes += nbytes
        if cur_bytes >= target_bytes:
            flush()
    flush()
    return buckets


def merged_span_length(spans: Sequence[Tuple[float, float]]) -> float:
    """Total length of the UNION of (start, end) intervals — transfer-busy
    wall time. Summing raw spans would double-count concurrent transfers
    (all pulls are issued up front, so their spans nest) and let a fully
    serial pipeline still report high overlap; the union is what the
    exposed stall is honestly compared against."""
    total = 0.0
    cur_start = cur_end = None
    for start, end in sorted(s for s in spans if s[1] > s[0]):
        if cur_end is None or start > cur_end:
            if cur_end is not None:
                total += cur_end - cur_start
            cur_start, cur_end = start, end
        elif end > cur_end:
            cur_end = end
    if cur_end is not None:
        total += cur_end - cur_start
    return total


@dataclass
class OffloadStats:
    """Per-step transfer/compute ledger.

    Every transfer interval is collected per direction and the ``*_s``
    occupancy values are the UNION of each direction's spans (concurrent
    pulls share one issue window — a sum would double-count them by the
    concurrency factor and understate effective GB/s; one convention for
    every direction). A span still covers any compute that ran under the
    transfer, so derived GB/s stays conservative. ``stall_s`` is the
    *exposed* time the step actually blocked waiting on a transfer — the
    number overlap exists to drive to zero; ``transfer_s`` (the all-
    direction union) is the denominator of overlap efficiency."""
    n_buckets: int = 0
    d2h_bytes: int = 0
    h2d_bytes: int = 0
    nvme_read_bytes: int = 0
    nvme_write_bytes: int = 0
    host_compute_s: float = 0.0
    stall_s: float = 0.0
    window_hwm_bytes: int = 0
    spans: List[Tuple[float, float]] = field(default_factory=list)
    dir_spans: Dict[str, List[Tuple[float, float]]] = field(
        default_factory=dict)
    extra: Dict[str, Any] = field(default_factory=dict)

    def add_span(self, direction: str, start: float, end: float) -> None:
        self.spans.append((start, end))
        self.dir_spans.setdefault(direction, []).append((start, end))

    @property
    def d2h_s(self) -> float:
        return merged_span_length(self.dir_spans.get("d2h", ()))

    @property
    def h2d_s(self) -> float:
        return merged_span_length(self.dir_spans.get("h2d", ()))

    @property
    def nvme_read_s(self) -> float:
        return merged_span_length(self.dir_spans.get("nvme_read", ()))

    @property
    def transfer_s(self) -> float:
        """Transfer-busy wall time: union of all transfer spans across
        directions (NVMe writes are fire-and-forget through the swapper's
        aio queue — their backpressure surfaces as read stall, not a
        separate span)."""
        return merged_span_length(self.spans)

    @property
    def overlap_efficiency(self) -> float:
        """See :func:`overlap_efficiency` (the canonical definition)."""
        return overlap_efficiency(self.stall_s, self.transfer_s)

    def as_dict(self) -> Dict[str, Any]:
        d = {
            "n_buckets": self.n_buckets,
            "d2h_bytes": self.d2h_bytes, "h2d_bytes": self.h2d_bytes,
            "nvme_read_bytes": self.nvme_read_bytes,
            "nvme_write_bytes": self.nvme_write_bytes,
            "d2h_s": self.d2h_s, "h2d_s": self.h2d_s,
            "nvme_read_s": self.nvme_read_s,
            "host_compute_s": self.host_compute_s,
            "stall_s": self.stall_s,
            "transfer_s": self.transfer_s,
            "overlap_efficiency": self.overlap_efficiency,
            "window_hwm_bytes": self.window_hwm_bytes,
        }
        d.update(self.extra)
        return d

    def merge_into(self, totals: Dict[str, float]) -> None:
        """Accumulate this step's ledger into a running-totals dict."""
        for k, v in self.as_dict().items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                if k in ("overlap_efficiency", "n_buckets",
                         "window_hwm_bytes"):
                    continue
                totals[k] = totals.get(k, 0.0) + v
        totals["window_hwm_bytes"] = max(
            totals.get("window_hwm_bytes", 0), self.window_hwm_bytes)
        totals["n_steps"] = totals.get("n_steps", 0) + 1


class ShardPull:
    """One async D2H grad-shard fetch: the ``jax.device_put`` to the host
    backend is issued at construction (non-blocking); :meth:`wait` is the
    single sanctioned blocking point and books exposed vs total time."""

    __slots__ = ("_fut", "_t_issue", "nbytes")

    def __init__(self, src: Any, host_device: Any):
        import jax

        self.nbytes = int(np.dtype(src.dtype).itemsize * np.prod(
            src.shape, dtype=np.int64)) if hasattr(src, "shape") else 0
        self._t_issue = time.perf_counter()
        self._fut = jax.device_put(src, host_device)

    def wait(self, stats: Optional[OffloadStats] = None) -> np.ndarray:
        t0 = time.perf_counter()
        arr = np.asarray(self._fut)
        t1 = time.perf_counter()
        if stats is not None:
            stats.stall_s += t1 - t0
            stats.d2h_bytes += self.nbytes
            stats.add_span("d2h", self._t_issue, t1)
        return arr


class MomentWindow:
    """Bounded prefetch window of Adam-moment buckets over the NVMe
    swapper.

    ``ensure(i)`` keeps buckets ``[i, i+window)`` in flight (reads issued,
    host buffers allocated); ``retrieve(i)`` blocks only on the tail of
    bucket *i*'s reads; ``retire(i)`` writes the updated moments back and
    drops every host reference — so at any instant at most ``window + 1``
    buckets of moments are host-resident (the window ahead plus the bucket
    whose write-back is being issued). ``hwm_bytes`` records the observed
    high-water and ``bound_bytes`` the contract it must stay under."""

    def __init__(self, swapper: Any, buckets: Sequence[Bucket],
                 window: int = 2):
        self.swapper = swapper
        self.buckets = list(buckets)
        self.window = max(1, int(window))
        self._next = 0
        #: bucket index -> {"t": issue time, "bytes": resident bytes,
        #:                  "mom": {(li, key): (m, v)} once retrieved}
        self._live: Dict[int, Dict[str, Any]] = {}
        self.resident_bytes = 0
        self.hwm_bytes = 0

    @property
    def bound_bytes(self) -> int:
        """The high-water contract: window+1 buckets of (m, v) pairs."""
        if not self.buckets:
            return 0
        biggest = max(b.nbytes for b in self.buckets)
        return (self.window + 1) * 2 * biggest

    @staticmethod
    def names(item: BucketItem) -> Tuple[str, str]:
        li, key, _ = item
        return f"m/{li}/{key}", f"v/{li}/{key}"

    def begin_step(self, stats: Optional[OffloadStats] = None) -> None:
        self._next = 0
        # re-stamp buckets surviving a skipped (overflow) step: their reads
        # completed long ago, and a span measured from the ORIGINAL issue
        # would book the whole skipped step as read occupancy — inflating
        # transfer_s and overstating overlap efficiency
        now = time.perf_counter()
        for info in self._live.values():
            info["t"] = now
        self.ensure(0, stats)

    def ensure(self, bi: int, stats: Optional[OffloadStats] = None) -> None:
        """Prefetch ahead so buckets ``[bi, bi+window)`` are in flight."""
        hi = min(max(bi + self.window, self._next), len(self.buckets))
        while self._next < hi:
            idx = self._next
            self._next += 1
            if idx in self._live:
                continue  # left in flight by a skipped (overflow) step
            b = self.buckets[idx]
            for item in b.items:
                for name in self.names(item):
                    self.swapper.prefetch(name)
            nbytes = 2 * b.nbytes
            self._live[idx] = {"t": time.perf_counter(), "bytes": nbytes}
            self.resident_bytes += nbytes
            self.hwm_bytes = max(self.hwm_bytes, self.resident_bytes)
            if stats is not None:
                stats.nvme_read_bytes += nbytes
                stats.window_hwm_bytes = self.hwm_bytes

    def retrieve(self, bi: int,
                 stats: Optional[OffloadStats] = None
                 ) -> Dict[Tuple[int, str], Tuple[np.ndarray, np.ndarray]]:
        """Block on bucket ``bi``'s prefetched reads; the wait is the
        exposed-stall ledger entry this window exists to minimize."""
        self.ensure(bi, stats)
        info = self._live[bi]
        t0 = time.perf_counter()
        mom: Dict[Tuple[int, str], Tuple[np.ndarray, np.ndarray]] = {}
        for item in self.buckets[bi].items:
            li, key, _ = item
            m_name, v_name = self.names(item)
            mom[(li, key)] = (self.swapper.retrieve(m_name),
                              self.swapper.retrieve(v_name))
        t1 = time.perf_counter()
        if stats is not None:
            stats.stall_s += t1 - t0
            stats.add_span("nvme_read", info["t"], t1)
        info["mom"] = mom
        return mom

    def retire(self, bi: int,
               stats: Optional[OffloadStats] = None) -> None:
        """Write the (updated-in-place) moments back and drop the host
        copies. The swapper retains each write buffer only until the write
        is confirmed durable (its retry contract), so retirement bounds
        OUR residency immediately."""
        info = self._live.pop(bi)
        mom = info.get("mom") or {}
        for item in self.buckets[bi].items:
            li, key, _ = item
            m, v = mom[(li, key)]
            m_name, v_name = self.names(item)
            self.swapper.swap_out(m_name, m)
            self.swapper.swap_out(v_name, v)
        self.resident_bytes -= info["bytes"]
        if stats is not None:
            stats.nvme_write_bytes += info["bytes"]
