"""Config key names and defaults (analog of ``deepspeed/runtime/constants.py``).

Key names intentionally match the reference JSON schema so existing DeepSpeed configs
parse unmodified (``train_batch_size``, ``zero_optimization``, ``bf16`` …). Keys whose
semantics are meaningless under XLA (cuda streams, nccl buckets) are accepted and
ignored with a warning rather than rejected, mirroring the reference's tolerance of
unknown accelerator-specific keys.
"""

# ---------------------------------------------------------------- batch family
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

# ---------------------------------------------------------------- optimizer
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = "adamw"
SCHEDULER = "scheduler"
MAX_GRAD_NORM = "max_grad_norm"
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

# ---------------------------------------------------------------- precision
FP16 = "fp16"
BF16 = "bf16"
FP32 = "fp32"
INITIAL_LOSS_SCALE_POWER = "initial_scale_power"
INITIAL_LOSS_SCALE_POWER_DEFAULT = 16
LOSS_SCALE_WINDOW = "loss_scale_window"
LOSS_SCALE_WINDOW_DEFAULT = 1000
MIN_LOSS_SCALE = "min_loss_scale"
MIN_LOSS_SCALE_DEFAULT = 1.0
HYSTERESIS = "hysteresis"
HYSTERESIS_DEFAULT = 2

# ---------------------------------------------------------------- zero
ZERO_OPTIMIZATION = "zero_optimization"
ZERO_STAGE = "stage"
ZERO_STAGE_DEFAULT = 0

# ---------------------------------------------------------------- parallelism
PARALLELISM = "parallelism"  # dstpu extension: mesh axis sizes
PIPELINE = "pipeline"
MOE = "moe"
SEQUENCE_PARALLEL_SIZE = "sequence_parallel_size"
TENSOR_PARALLEL = "tensor_parallel"

# ---------------------------------------------------------------- misc engine
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
PRESCALE_GRADIENTS = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
DUMP_STATE = "dump_state"
SEED = "seed"
SEED_DEFAULT = 42

# ---------------------------------------------------------------- subsystems
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
COMMS_LOGGER = "comms_logger"
MONITOR_TENSORBOARD = "tensorboard"
MONITOR_WANDB = "wandb"
MONITOR_CSV = "csv_monitor"
MONITOR_JSONL = "jsonl_monitor"
TELEMETRY = "telemetry"
FLOPS_PROFILER = "flops_profiler"
ELASTICITY = "elasticity"
COMPRESSION_TRAINING = "compression_training"
DATA_EFFICIENCY = "data_efficiency"
CHECKPOINT = "checkpoint"
OFFLOAD_OPTIMIZER = "offload_optimizer"
OFFLOAD_PARAM = "offload_param"
AUTOTUNING = "autotuning"

# Keys from the reference schema that have no XLA analog; accepted + ignored.
IGNORED_REFERENCE_KEYS = frozenset({
    "communication_data_type",
    "sparse_gradients",
    "fp16_master_weights_and_gradients",
    "amp",
    "disable_allgather",
    "cuda_graphs",
    "memory_breakdown",
    "sparse_attention",
})
