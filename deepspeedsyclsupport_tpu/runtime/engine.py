"""Training engine.

TPU-native analog of ``DeepSpeedEngine`` (``deepspeed/runtime/engine.py:179``, 3600 LoC)
and ``deepspeed.initialize`` (``deepspeed/__init__.py:64``): one config-driven object
wrapping a model with composed parallelism, precision policy, optimizer, LR schedule,
checkpointing, monitoring, and throughput accounting.

Structural shift from the reference (why this file is ~10× smaller):

* ``forward/backward/step`` there are eager passes threaded through hooks, buckets,
  and streams. Here the whole micro-step — forward, backward, grad accumulation,
  reduction, clip, optimizer, loss-scale bookkeeping — is ONE jitted SPMD program
  (``_build_train_batch_fn``), with gradient accumulation as ``lax.scan`` so it
  compiles once regardless of accumulation depth.
* ZeRO stages are placement policy (``runtime/zero.py``), not optimizer subclasses:
  the same train step serves stages 0-3; XLA inserts the all-gather/reduce-scatter
  traffic the reference implements by hand (``stage_1_and_2.py:1004``, ``stage3.py``).
* DP gradient averaging (reference ``allreduce_gradients`` ``engine.py:1903``) falls
  out of computing the *global* mean loss over a batch sharded on (data, fsdp).

The eager ``forward()/backward()/step()`` triple is still provided for loop parity
with reference user code, implemented over the same jitted kernels.
"""
import glob as glob_mod
import json
import os
import sys
import time
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import zero as zero_lib
from .config import DSTpuConfig
from .dataloader import DSTpuDataLoader
from .loss_scaler import (LossScaleState, grads_finite, init_loss_scale, scale_loss,
                          unscale_grads, update_loss_scale)
from .lr_schedules import build_schedule
from .optimizers import build_optimizer, current_lr
from .sentinel import SENTINEL_GATE_KEY
from ..checkpoint.engine import LATEST_FILE
from ..comm.comms_logging import comms_logger
from ..comm.topology import MeshTopology, build_topology
from ..utils.fault_injection import get_fault_injector
from ..monitor import MonitorMaster
from ..utils.logging import log_dist, logger
from ..utils.timer import (BACKWARD_GLOBAL_TIMER, FORWARD_GLOBAL_TIMER,
                           STEP_GLOBAL_TIMER, SynchronizedWallClockTimer,
                           ThroughputTimer)


class _InitTuple(NamedTuple):
    """Return shape of :func:`initialize` for reference-style unpacking
    ``engine, optimizer, dataloader, lr_scheduler = initialize(...)``."""
    engine: "Engine"
    optimizer: Any
    training_dataloader: Any
    lr_scheduler: Any


def initialize(model: Any = None,
               loss_fn: Optional[Callable] = None,
               params: Any = None,
               config: Any = None,
               topology: Optional[MeshTopology] = None,
               training_data: Any = None,
               lr_schedule: Optional[Callable] = None,
               sharding_rules: Optional[Callable] = None,
               mpu: Any = None,
               dist_init_required: Optional[bool] = None,
               collate_fn: Optional[Callable] = None,
               config_params: Any = None) -> _InitTuple:
    """Build an :class:`Engine` (reference: ``deepspeed.initialize``,
    ``deepspeed/__init__.py:64``; arg names kept where meaningful).

    ``model``: anything exposing ``loss(params, batch, rng) -> loss | (loss, aux)``
    (our ``models/`` follow this protocol) — or pass ``loss_fn`` directly.
    ``params``: the initial parameter pytree (host arrays fine; engine places them).
    """
    from ..comm import init_distributed

    config = config if config is not None else config_params
    if config is None:
        raise ValueError("config (dict or json path) is required")
    init_distributed(dist_init_required=dist_init_required)

    if loss_fn is None:
        if model is None or not hasattr(model, "loss"):
            raise ValueError("provide loss_fn, or a model with a .loss method")
        loss_fn = model.loss
    if params is None:
        if model is not None and hasattr(model, "init_params"):
            params = model.init_params()
        else:
            raise ValueError("provide params, or a model with init_params()")
    if sharding_rules is None and model is not None:
        sharding_rules = getattr(model, "sharding_rules", None)

    engine = Engine(loss_fn=loss_fn, params=params, config=config,
                    topology=topology, lr_schedule=lr_schedule,
                    sharding_rules=sharding_rules, module=model)
    dataloader = None
    if training_data is not None:
        dataloader = engine.register_dataloader(
            DSTpuDataLoader(training_data, engine.topology,
                            batch_fn=collate_fn))
    return _InitTuple(engine, engine.optimizer, dataloader, engine.lr_schedule)


class Engine:
    def __init__(self, loss_fn: Callable, params: Any, config: Any,
                 topology: Optional[MeshTopology] = None,
                 lr_schedule: Optional[Callable] = None,
                 sharding_rules: Optional[Callable] = None,
                 module: Any = None):
        self.module = module
        self.loss_fn_raw = loss_fn
        import inspect

        try:
            self._loss_accepts_train = "train" in inspect.signature(
                loss_fn).parameters
        except (TypeError, ValueError):
            self._loss_accepts_train = False
        self.config = DSTpuConfig.from_config(config)

        # ---------------------------------------------------------- topology
        p = self.config.parallelism
        self.topology = topology or build_topology(dp=p.dp, fsdp=p.fsdp, tp=p.tp,
                                                   pp=p.pp, ep=p.ep, sp=p.sp)
        self.dp_world_size = self.topology.get_data_parallel_world_size()
        self.config.resolve_batch_sizes(self.dp_world_size)
        # Model-config overrides (pipe trunk, remat, random-LTD) are
        # COLLECTED here and applied to a per-engine private copy at the end
        # of __init__ — the engine never mutates a shared model's config in
        # place, so two engines on one model each trace their own
        # configuration (reference: PipelineEngine owns its stage count;
        # micro_batches is the pipeline.micro_batches knob).
        mcfg = getattr(self.module, "config", None)
        mcfg_overrides: Dict[str, Any] = {}
        if hasattr(mcfg, "pipe_stages"):
            # make the pipelined trunk an explicit model-config property
            mcfg_overrides["pipe_stages"] = self.topology.axis_sizes["pipe"]
            if p.pp_microbatches:
                mcfg_overrides["pipe_microbatches"] = p.pp_microbatches

        comms_logger.configure(enabled=self.config.comms_logger.enabled,
                               verbose=self.config.comms_logger.verbose)

        from ..checkpoint.ckpt_engine import build_checkpoint_engine

        self.checkpoint_engine = build_checkpoint_engine(
            self.config.checkpoint.engine)

        self.progressive_layer_drop = None
        if self.config.progressive_layer_drop.enabled:
            from .progressive_layer_drop import ProgressiveLayerDrop

            if self.config.data_efficiency.random_ltd is not None:
                raise ValueError(
                    "progressive_layer_drop and random_ltd cannot be "
                    "combined (both restructure the layer stack)")
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=self.config.progressive_layer_drop.theta,
                gamma=self.config.progressive_layer_drop.gamma)

        # ---------------------------------------------------------- precision
        self.compute_dtype = self.config.compute_dtype
        fp16 = self.config.fp16
        self.fp16_enabled = fp16.enabled
        self.scaler_state = init_loss_scale(
            fp16.initial_scale if fp16.enabled else 1.0,
            dynamic=fp16.enabled and fp16.dynamic,
            hysteresis=fp16.hysteresis)

        # ---------------------------------------------------------- zero++
        zc = self.config.zero
        self._zeropp_enabled = (zc.zero_quantized_weights
                                or zc.zero_quantized_gradients
                                or zc.zero_hpz_partition_size > 1)
        if self._zeropp_enabled:
            axes = self.topology.axis_sizes
            n = axes["fsdp"]
            # TP composes: the explicit step is partially manual over
            # {data, fsdp} and leaves the model axis to XLA's partitioner
            # (reference runs hpZ/qwZ with Megatron TP —
            # ``partition_parameters.py:1551``, ``engine.py:849-858``)
            bad = [a for a in ("pipe", "seq", "expert") if axes[a] > 1]
            if zc.stage != 3 or n <= 1 or bad:
                raise ValueError(
                    f"ZeRO++ flags need stage 3 on a data/fsdp[/model] mesh "
                    f"with fsdp>1 (stage={zc.stage}, fsdp={n}, "
                    f"unsupported axes in use: {bad})")
            h = zc.zero_hpz_partition_size
            if h > 1 and n % h:
                raise ValueError(
                    f"zero_hpz_partition_size {h} must divide fsdp {n}")
            # offload composes: the explicit step's grads-only variant
            # feeds the host-resident master update (_build_grads_batch_fn)

        # ---------------------------------------------------------- optimizer
        sched_cfg = self.config.scheduler
        self.lr_schedule = lr_schedule or build_schedule(
            sched_cfg.type, sched_cfg.params, self.config.optimizer.lr)
        tx = build_optimizer(self.config.optimizer.type, self.config.optimizer.params,
                             self.lr_schedule)
        if (self.config.gradient_clipping and self.config.gradient_clipping > 0
                and not self._zeropp_enabled):
            # zero++ clips manually inside shard_map: optax's global-norm
            # transform would compute a per-shard norm there
            tx = optax.chain(
                optax.clip_by_global_norm(self.config.gradient_clipping), tx)
        self.optimizer = tx

        # ---------------------------------------------------------- placement
        stage = self.config.zero.stage
        self.zero_stage = stage
        self.param_shardings = zero_lib.tree_param_shardings(
            params, self.topology, stage, extra_rules=sharding_rules)
        # Stage >= 2: gradients (and the fp32 grad accumulator the scan
        # carries) live fsdp-sharded — the reference's IPG reduce-scatter
        # bucketing (``stage_1_and_2.py:894,1004``). The layout is exactly
        # the stage-3 param layout (TP dims composed, largest free dim over
        # fsdp). Computed before offload init: the multi-host offload path
        # reuses it as its shard layout.
        self.grad_shardings = None
        if stage >= 2 and self.topology.axis_sizes["fsdp"] > 1:
            self.grad_shardings = zero_lib.tree_param_shardings(
                params, self.topology, 3, extra_rules=sharding_rules)

        # -------------------------------------------------------- offload
        # ZeRO-Offload / ZeRO-Infinity (reference: cpu_adam host step
        # ``csrc/adam/cpu_adam.cpp``, stage3 optimizer-state swap
        # ``stage3.py:1816``, NVMe prefetch
        # ``partitioned_param_coordinator.py:503``). When enabled, the
        # device holds only compute-dtype working params; fp32 master
        # params + optimizer moments live on the host CPU backend, where the
        # update step runs as a second jitted program; 'nvme' additionally
        # round-trips the moments through the async swapper between steps.
        off_opt = self.config.zero.offload_optimizer
        off_par = self.config.zero.offload_param
        self.offload_device = None
        self._mh_offload = None     # multi-controller per-host shard swapping
        self._mh_push_fn = None
        self._multihost = False
        if off_opt.enabled or off_par.enabled:
            if jax.process_count() > 1:
                # per-host shard swapping (reference: CPUAdam partition
                # updates per rank + cross-rank grad-norm allreduce,
                # stage_1_and_2.py cpu_offload / stage3.py:1816): each
                # controller owns its fsdp shard's fp32 master + moments
                t = self.config.optimizer.type.lower().replace("_", "")
                if off_par.device == "nvme":
                    # multi-host NVMe swap covers OPTIMIZER state (the
                    # moments); parameter NVMe offload is single-controller
                    # only — accepting it here would silently leave params
                    # resident and OOM a ZeRO-Infinity-sized model
                    raise NotImplementedError(
                        "multi-host offload_param device='nvme' is not "
                        "wired; use offload_optimizer device='nvme' "
                        "(per-host moment swap) or offload_param='cpu'")
                if t not in ("adam", "adamw", "fusedadam", "cpuadam"):
                    raise ValueError(
                        "multi-host offload implements CPU Adam/AdamW only "
                        "(the reference's CPUAdam is likewise the only "
                        "offload optimizer); got optimizer type "
                        f"{self.config.optimizer.type!r}")
                if stage < 2 or self.topology.axis_sizes["fsdp"] <= 1:
                    raise ValueError(
                        "multi-host offload needs zero stage >= 2 with "
                        "fsdp > 1 so gradients land host-disjoint")
                self._multihost = True
            self.offload_device = ("nvme" if "nvme" in (off_opt.device,
                                                        off_par.device)
                                   else "cpu")
        self._swapper = None
        if self.offload_device is not None:
            self._init_offload(params, tx, off_opt, off_par)
        else:
            self.master_params = None
            self.params = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(jnp.asarray(x), s), params,
                self.param_shardings)
            opt_shapes = jax.eval_shape(tx.init, self.params)
            self.opt_shardings = zero_lib.tree_optimizer_shardings(
                opt_shapes, self.params, self.param_shardings, self.topology,
                stage)
            self.opt_state = jax.jit(
                tx.init, out_shardings=self.opt_shardings)(self.params)
        log_dist(zero_lib.describe_memory_plan(self.params, self.topology,
                                               stage, self.offload_device))

        # ---------------------------------------------------------- step fns
        self._train_batch_fn = None  # built lazily (needs gas)
        self._grad_fn = None
        self._apply_fn = None
        self._eval_fn = None
        self._host_apply = None

        # ---------------------------------------------------------- bookkeeping
        self.global_steps = 0
        self.micro_steps = 0
        self._accum_grads = None
        self._accum_count = 0
        self._accum_losses = []
        self._pending_events = []  # buffered monitor samples (see _post_step)
        self._resilience = None  # ResilienceManager (enable_preemption_handling)
        self._resilience_reported = {}  # last counter values flushed to monitor
        self._last_batch = None
        self._rng = jax.random.PRNGKey(self.config.seed)
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.config.train_batch_size,
            steps_per_output=self.config.steps_per_print)
        self.monitor = MonitorMaster(self.config.monitor)
        # Structured observability spine (monitor/telemetry.py): flight
        # recorder ring + rank-local JSONL, goodput accounting, recompile
        # detection, HBM gauges, heartbeat. None when the telemetry section
        # is off and DSTPU_TELEMETRY doesn't force it — the per-step guards
        # below then cost one attribute check.
        from ..monitor.telemetry import build_telemetry

        self.telemetry = build_telemetry(self.config, self.monitor)
        if self.telemetry is not None:
            # barrier-anchored alignment point for cross-rank trace fusion
            # (monitor/pod.py): engine construction is collective under
            # multiple controllers, so every rank stamps the same physical
            # instant through its own wall clock — the pod aggregator's
            # clock-offset ground truth. Single-process: a local marker.
            self.telemetry.anchor("engine_init")
        # Collective hang watchdog (comm/watchdog.py): a deadline armed
        # around each step's collective dispatch; expiry = stack dump +
        # recorder flush + rc-218 exit, the comm-hang contract the elastic
        # agent restarts distinctly from crash and preemption.
        self._watchdog = None
        # pod identity (utils/podid.py): jax.process_index under real
        # multi-controller, the env-declared RANK for pods of independent
        # single-controller replicas — rank-targeted fault injection and
        # the watchdog's rank labeling both key on it
        from ..utils.podid import pod_rank

        self._fi_rank = pod_rank()
        tw = self.config.telemetry
        if self.telemetry is not None and tw.watchdog_enabled:
            from ..comm.watchdog import CollectiveWatchdog

            self._watchdog = CollectiveWatchdog(
                deadline_s=tw.watchdog_deadline_s,
                warmup_deadline_s=tw.watchdog_warmup_deadline_s,
                poll_s=tw.watchdog_poll_s,
                rank=self._fi_rank,
                telemetry=self.telemetry,
                stack_path=os.path.join(
                    tw.output_dir, f"stacks_rank{self._fi_rank}.txt"),
            ).start()
            # telemetry.close() owns shutdown of the poll thread (engines
            # have no teardown of their own)
            self.telemetry.watchdog = self._watchdog

        # -------------------------------------------- activation checkpointing
        # (reference runtime/activation_checkpointing/: config-driven
        # save/recompute; here the section turns on jax.checkpoint around
        # each model layer and selects the rematerialization policy)
        if "activation_checkpointing" in self.config.raw:
            ac = self.config.activation_checkpointing
            if mcfg is None or not hasattr(mcfg, "remat"):
                logger.warning(
                    "activation_checkpointing configured but the model does "
                    "not expose a remat flag; apply jax.checkpoint in your "
                    "model instead")
            elif ac.enabled:
                # section presence = on (ported reference configs carry
                # partition_activations=false and still expect remat)
                if ac.cpu_checkpointing:
                    # reference cpu_checkpointing: saved activations move to
                    # host instead of recomputing — the XLA host-offload
                    # remat policy
                    mcfg_overrides["remat"] = True
                    mcfg_overrides["remat_policy"] = "offload_dots_to_host"
                    log_dist("cpu_checkpointing: dot activations offload to "
                             "pinned host memory")
                else:
                    mcfg_overrides["remat"] = True
                    mcfg_overrides["remat_policy"] = ac.policy
                    log_dist(f"activation checkpointing on "
                             f"(policy={ac.policy})")
            else:
                # explicit "enabled": false turns remat OFF — the
                # autotuner's off-arm on a shared model object. It also wins
                # over a contradictory cpu_checkpointing=true in the same
                # section (the explicit off-switch is authoritative).
                mcfg_overrides["remat"] = False
                if ac.cpu_checkpointing:
                    logger.warning(
                        "cpu_checkpointing requested but activation_"
                        "checkpointing.enabled is false — the explicit "
                        "off-switch wins; activations are not offloaded")

        # ------------------------------------------------- data efficiency
        # (reference: deepspeed/runtime/data_pipeline/ — curriculum seqlen
        # schedule + random-LTD token-drop schedule, both config-driven)
        de = self.config.data_efficiency
        self.curriculum_scheduler = None
        self.random_ltd_scheduler = None
        self._rltd_value = None
        if de.curriculum is not None:
            from .data_pipeline import CurriculumScheduler

            self.curriculum_scheduler = CurriculumScheduler(de.curriculum)
        if de.random_ltd is not None:
            from .data_pipeline import RandomLTDScheduler

            self.random_ltd_scheduler = RandomLTDScheduler(de.random_ltd)
            if mcfg is None:
                raise ValueError("random_ltd needs a framework model "
                                 "(models.CausalLM) to drive token dropping")
            if not getattr(mcfg, "scan_layers", False) or \
                    getattr(mcfg, "num_layers", 0) < 3:
                raise ValueError(
                    "random_ltd requires a scan_layers model with >= 3 "
                    "layers (first/last stay dense; the middle stack drops "
                    "tokens) — got scan_layers="
                    f"{getattr(mcfg, 'scan_layers', None)}, num_layers="
                    f"{getattr(mcfg, 'num_layers', None)}")
            mcfg_overrides["random_ltd"] = True

        # -------------------------------------------- per-engine model view
        # Apply the collected config overrides to a PRIVATE shallow clone of
        # the model, and rebind a model-bound loss_fn onto the clone. The
        # caller's model object is left untouched: engines sharing one model
        # can no longer silently retrace each other's trunk (the r3
        # "functions traced earlier keep the old trunk" hazard), and the
        # per-step random-LTD keep-count mutation lands on engine-owned
        # state only.
        if self.module is not None and mcfg is not None and mcfg_overrides:
            import copy

            view_cfg = copy.copy(mcfg)
            for name, value in mcfg_overrides.items():
                setattr(view_cfg, name, value)
            view = copy.copy(self.module)
            view.config = view_cfg
            if getattr(self.loss_fn_raw, "__self__", None) is self.module:
                self.loss_fn_raw = getattr(view, self.loss_fn_raw.__name__)
            else:
                # a closure/partial loss_fn capturing the ORIGINAL model
                # cannot be rebound: it will trace the caller's config and
                # silently miss these overrides
                logger.warning(
                    "model-config overrides %s apply to the engine's "
                    "private model view, but the provided loss_fn is not a "
                    "bound method of the model and may still read the "
                    "original config — pass the model (engine binds "
                    "model.loss itself) or read config from the engine's "
                    "module", sorted(mcfg_overrides))
            self.module = view
        # --------------------------------------------------- QAT (in-forward)
        # reference runtime/quantize.py Quantizer: progressive bit schedule
        # over weight groups; compute copies are STE-fake-quantized in the
        # forward while the fp32 master stays exact
        from ..compression.qat import parse_qat_config

        self.qat_scheduler = parse_qat_config(self.config.raw)
        self._qat_bits: Dict[int, int] = {}
        if self.qat_scheduler is not None:
            # sync NOW: eval_batch/forward before the first train_batch must
            # already see the step-0 precision
            self._qat_bits, _ = self.qat_scheduler.update(0)

        from ..profiling.flops_profiler import FlopsProfiler

        self.flops_profiler = FlopsProfiler(self)
        # XLA timeline capture (the reference's NVTX-range story,
        # ``utils/nvtx.py`` + wall_clock_breakdown, recast as jax.profiler
        # traces viewable in TensorBoard/Perfetto): config section
        # {"jax_profiler": {"enabled": true, "trace_dir": ..., "start_step":
        # N, "num_steps": M}} brackets M train steps with a device trace
        jp = dict(self.config.raw.get("jax_profiler", {}))
        tcfg = self.config.telemetry
        if tcfg.trace_start_step is not None and \
                (tcfg.enabled or self.telemetry is not None):
            # telemetry.trace is the newer spelling of the same window knobs
            jp = {"enabled": True, "start_step": tcfg.trace_start_step,
                  "num_steps": tcfg.trace_num_steps,
                  "trace_dir": tcfg.trace_dir or jp.get("trace_dir")}
        env_start = os.environ.get("DSTPU_TRACE_START_STEP")
        if env_start:
            # env-triggered trace window: profile a misbehaving production
            # run without touching its config. A malformed value must not
            # kill the run the operator is trying to observe.
            try:
                jp = {"enabled": True, "start_step": int(env_start),
                      "num_steps": int(os.environ.get(
                          "DSTPU_TRACE_NUM_STEPS", jp.get("num_steps", 3))),
                      "trace_dir": (os.environ.get("DSTPU_TRACE_DIR")
                                    or jp.get("trace_dir"))}
            except ValueError as e:
                logger.warning(
                    "ignoring malformed DSTPU_TRACE_START_STEP/"
                    "DSTPU_TRACE_NUM_STEPS (%s); no trace window armed", e)
        self._trace_cfg = jp if jp.get("enabled") else None
        self._tracing = False
        self._trace_origin = None  # "config" windows auto-stop; manual don't
        # MFU-ledger window (telemetry.mfu): one-shot capture of a clean
        # (non-compiling) step into its own profiler trace dir; the join
        # against the roofline partition happens in mfu_ledger()
        self._mfu_pending = bool(self.telemetry is not None
                                 and tcfg.mfu_enabled)
        self._mfu_window = None
        self._mfu_attempts = 0
        self._mfu_compile_base = 0
        self._mfu_trace_dir = os.path.join(
            tcfg.output_dir, f"mfu_trace_rank{self._fi_rank}")
        # ------------------------------------------------ training sentinel
        # numerical-fault watchdog (runtime/sentinel.py): in-graph health
        # scalars + host-side spike detection + the warn/skip/rollback/abort
        # ladder. The registered dataloader (register_dataloader) is what
        # rollback rewinds; None when the section is off.
        self._dataloader = None
        self._sentinel = None
        if self.config.sentinel.enabled:
            from .sentinel import TrainingSentinel

            self._sentinel = TrainingSentinel(self, self.config.sentinel,
                                              rank=self._fi_rank)
        self.losses = None

    # ================================================================ offload
    def _init_offload(self, params, tx, off_opt, off_par):
        """Host-resident fp32 master + moments; compute-dtype device params."""
        pipe_cfg = off_opt if off_opt.enabled else off_par
        t = self.config.optimizer.type.lower().replace("_", "")
        adam_like = t in ("adam", "adamw", "fusedadam", "cpuadam")
        if not self._multihost and pipe_cfg.pipeline and not adam_like:
            # the pipelined host engine is a CPU Adam (the reference's
            # CPUAdam is likewise the only offload optimizer); other optax
            # optimizers keep the legacy jitted host path below
            log_dist(f"offload pipeline needs an Adam-family optimizer "
                     f"(got {self.config.optimizer.type!r}); using the "
                     f"jitted host-apply path")
        if self._multihost or (pipe_cfg.pipeline and adam_like):
            # Bucketed D2H / host-Adam / H2D pipeline with the bounded
            # NVMe moment window (runtime/multihost_offload.py +
            # offload_pipeline.py). Topology-agnostic: with one controller
            # the grad-norm allreduce degenerates to identity and the same
            # engine serves single-host ZeRO-Offload.
            from .multihost_offload import MultiHostCPUAdam
            from .optimizers import _common

            opt_params = self.config.optimizer.params
            _, betas, eps, wd = _common(opt_params)
            # mirror build_optimizer: plain "adam" with adam_w_mode=False is
            # optax.adam — no weight decay at all
            if t == "adam" and not opt_params.get("adam_w_mode", True):
                wd = 0.0
            fp16 = self.config.fp16
            mh_swapper = None
            if self.offload_device == "nvme":
                # ZeRO-Infinity across controllers: each host swaps ITS
                # moment shards to its own NVMe path (reference: every
                # rank swaps its own partition, stage3.py:1816). Private
                # to the optimizer — the engine's single-controller
                # _swapper machinery keys on opt_state, which is None here
                from .swap_tensor import AsyncTensorSwapper

                nvme_path = (off_opt.nvme_path or off_par.nvme_path
                             or os.path.join(os.getcwd(),
                                             "dstpu_nvme_swap"))
                mh_swapper = AsyncTensorSwapper(os.path.join(
                    nvme_path, f"rank{jax.process_index()}"))
            self._mh_offload = MultiHostCPUAdam(
                params,
                # shard layout: the ZeRO-3 grad layout when fsdp shards
                # exist, else the working-param layout (single controller /
                # fsdp=1 — every shard is host-addressable either way)
                self.grad_shardings if self.grad_shardings is not None
                else self.param_shardings,
                betas=betas, eps=eps,
                weight_decay=wd,
                clip=self.config.gradient_clipping,
                lr_fn=lambda step: float(np.asarray(
                    self.lr_schedule(step)
                    if callable(self.lr_schedule) else self.lr_schedule)),
                fp16_cfg=fp16, fp16_enabled=self.fp16_enabled,
                swapper=mh_swapper,
                bucket_bytes=pipe_cfg.bucket_size,
                window_buckets=pipe_cfg.buffer_count,
                overlap=pipe_cfg.overlap,
                push_dtype=jnp.dtype(self.compute_dtype))
            # the host CPU Adam runs the loss-scale state machine on host
            # (host_update_loss_scale): keep the state numpy-resident so
            # its per-step scale read is a plain float, never a device sync
            from .loss_scaler import host_loss_scale_state

            self.scaler_state = host_loss_scale_state(self.scaler_state)
            self.master_params = None
            self.opt_state = None
            self.opt_shardings = None
            self.params = self._push_params_to_device(params)
            return
        cpu = jax.local_devices(backend="cpu")[0]
        self._cpu_device = cpu

        def to_master(x):
            # async transfer to the host device (no blocking device_get
            # round trip); the fp32 promotion then runs on the host backend
            x = jax.device_put(x, cpu)
            if jnp.issubdtype(x.dtype, jnp.floating):
                x = x.astype(jnp.float32)
            return x

        self.master_params = jax.tree_util.tree_map(to_master, params)
        self.params = self._push_params_to_device(params)
        # master is cpu-committed, so jit compiles this for the host backend
        self.opt_state = jax.jit(tx.init)(self.master_params)
        self.opt_shardings = jax.tree_util.tree_map(
            lambda _: cpu, self.opt_state)
        if self.offload_device == "nvme":
            from .swap_tensor import AsyncTensorSwapper

            nvme_path = (off_opt.nvme_path or off_par.nvme_path
                         or os.path.join(os.getcwd(), "dstpu_nvme_swap"))
            self._swapper = AsyncTensorSwapper(os.path.join(
                nvme_path, f"rank{jax.process_index()}"))
            self._swap_out_opt_state()
        log_dist(f"offload: master+optimizer on "
                 f"{'NVMe(' + self._swapper.swap_dir + ')' if self._swapper else 'host CPU'}, "
                 f"device params dtype={jnp.dtype(self.compute_dtype).name}")

    def _mh_push(self, master_tree):
        """Jitted cast+reshard: shard (ZeRO-3) layout fp32 master → working
        param layout in compute dtype; any cross-host gather rides the
        ICI/DCN interconnect on device, never the hosts."""
        if self._mh_push_fn is None:
            dtype = self.compute_dtype

            def push(t):
                return jax.tree_util.tree_map(
                    lambda x: x.astype(dtype)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x, t)

            self._mh_push_fn = jax.jit(push,
                                       out_shardings=self.param_shardings)
        return self._mh_push_fn(master_tree)

    def _push_params_to_device(self, master_tree):
        """Compute-dtype device working copies from the fp32 host master.
        The cast runs where each leaf already lives (the host backend for
        cpu-committed masters, numpy for raw init trees) and the transfer
        is an async ``device_put`` — no blocking ``device_get`` round trip
        and no transient commit to the default device (this runs once per
        step on the offload path)."""
        dtype = self.compute_dtype

        def push(x, s):
            if jnp.issubdtype(jnp.result_type(x), jnp.floating):
                x = x.astype(dtype)
            return jax.device_put(x, s)

        return jax.tree_util.tree_map(push, master_tree, self.param_shardings)

    def _swap_out_opt_state(self):
        """Moments → NVMe; drop the host copies (keeps shapes/treedef only)."""
        from ..checkpoint.engine import _leaf_paths

        self._opt_treedef = jax.tree_util.tree_structure(self.opt_state)
        leaves = jax.tree_util.tree_leaves(self.opt_state)
        self._opt_example = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x),
                                           np.asarray(x).dtype),
            self.opt_state)
        names = _leaf_paths(self._opt_example)
        self._opt_names = names
        for name, leaf in zip(names, leaves):
            self._swapper.swap_out("opt/" + name, leaf)
        self.opt_state = None  # host memory released; state lives on disk

    def _prefetch_opt_state(self):
        for name in self._opt_names:
            self._swapper.prefetch("opt/" + name)

    def _swap_in_opt_state(self):
        leaves = [jax.device_put(self._swapper.retrieve("opt/" + n),
                                 self._cpu_device)
                  for n in self._opt_names]
        self.opt_state = jax.tree_util.tree_unflatten(self._opt_treedef,
                                                      leaves)

    def _build_grads_batch_fn(self):
        """Device half of the offloaded step: scan microbatches → grads."""
        if self._zeropp_enabled:
            from .zeropp import build_zeropp_grads_fn

            return build_zeropp_grads_fn(self)
        gas = self.config.gradient_accumulation_steps

        def grads_fn(params, scaler, batch, rng):
            def micro(carry, mb):
                acc, i = carry
                loss, metrics, grads = self._micro_grads(
                    params, mb, jax.random.fold_in(rng, i), scaler)
                acc = jax.tree_util.tree_map(jnp.add, acc, grads)
                return (acc, i + 1), (loss, metrics)

            if gas == 1:
                loss, metrics, grads = self._micro_grads(params, batch, rng,
                                                         scaler)
                return grads, loss[None], metrics
            if self.grad_shardings is not None:
                # same 1/N accumulator layout as the fused path — this is the
                # device memory offload exists to save
                zero_grads = jax.tree_util.tree_map(
                    lambda p, s: jax.lax.with_sharding_constraint(
                        jnp.zeros(p.shape, jnp.float32), s),
                    params, self.grad_shardings)
            else:
                zero_grads = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params)
            (grads, _), (losses, metrics) = jax.lax.scan(
                micro, (zero_grads, 0), batch)
            grads = jax.tree_util.tree_map(lambda g: g / gas, grads)
            metrics = jax.tree_util.tree_map(lambda m: m.mean(axis=0), metrics)
            return grads, losses, metrics

        return jax.jit(grads_fn)

    def _build_host_apply_fn(self):
        """Host half (the cpu_adam analog): fp32 master update on the CPU
        backend, returns the new master tree + scalar step metrics."""

        def apply_fn(master, opt_state, scaler, grads):
            new_master, new_opt, new_scaler, finite, grad_norm, _ = \
                self._apply_grads(master, opt_state, scaler, grads)
            return new_master, new_opt, new_scaler, {
                "grad_norm": grad_norm, "finite": finite,
                "loss_scale": new_scaler.scale}

        # all inputs are cpu-committed → compiles for the host backend
        return jax.jit(apply_fn, donate_argnums=(0, 1))

    def _offload_train_batch(self, batch, rng):
        if self._train_batch_fn is None:
            self._train_batch_fn = self._build_grads_batch_fn()
        if self._swapper is not None:
            self._prefetch_opt_state()  # overlap disk read with device grads
        # scaler lives host-side between steps (the update runs there);
        # replicate it onto the mesh for the device half
        dev_scaler = jax.device_put(self.scaler_state,
                                    self.topology.replicated())
        grads, losses, metrics = self._train_batch_fn(
            self.params, dev_scaler, batch, rng)
        m2 = self._host_step(grads)
        out = dict(metrics)
        out.update({k: m2[k] for k in ("grad_norm", "finite", "loss_scale")})
        out["loss"] = losses.mean()
        return out

    def _host_step(self, grads):
        """Shared tail of an offloaded step: grads → host, (swap in,) fp32
        master update on CPU, (swap out,) push compute-dtype params back."""
        if self._mh_offload is not None:
            new_master, self.scaler_state, m2 = self._mh_offload.step(
                grads, self.scaler_state)
            self.params = self._mh_push(new_master)
            # per-step transfer/stall ledger for telemetry (picked up by
            # on_step_end → Offload/* events + the goodput offload_stall
            # bucket); stash-and-pop so an eval between steps can't
            # double-report it
            self._last_offload_stats = self._mh_offload.last_stats
            return m2
        if self._host_apply is None:
            self._host_apply = self._build_host_apply_fn()
        # async device->host transfers (XLA gathers shards in flight); the
        # old device_get round trip blocked the dispatch pipeline here every
        # step — the host apply below is the only consumer that must wait
        host_grads = jax.tree_util.tree_map(
            lambda g: jax.device_put(g, self._cpu_device), grads)
        if self._swapper is not None and self.opt_state is None:
            self._swap_in_opt_state()
        scaler = jax.device_put(self.scaler_state, self._cpu_device)
        self.master_params, self.opt_state, self.scaler_state, m2 = \
            self._host_apply(self.master_params, self.opt_state,
                             scaler, host_grads)
        if self._swapper is not None:
            self._swap_out_opt_state()
        self.params = self._push_params_to_device(self.master_params)
        return m2

    # ================================================================ loss core
    def _cast_params(self, params):
        dtype = self.compute_dtype
        return jax.tree_util.tree_map(
            lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
            params)

    def _loss_and_metrics(self, params, batch, rng, train=True):
        p = self._cast_params(params)
        if self.qat_scheduler is not None and self._qat_bits:
            # eval included: QAT's point is measuring at deployment
            # precision (reference quantize_weight_in_forward quantizes the
            # module forward unconditionally)
            from ..compression.qat import apply_qat

            p = apply_qat(p, self._qat_bits, self.qat_scheduler.groups,
                          self.qat_scheduler.symmetric)
        if self._loss_accepts_train:
            out = self.loss_fn_raw(p, batch, rng, train=train)
        else:
            # user loss fns without a train flag (no train-time stochastic
            # behavior to gate)
            out = self.loss_fn_raw(p, batch, rng)
        if isinstance(out, tuple):
            loss, metrics = out
            metrics = dict(metrics)
        else:
            loss, metrics = out, {}
        return loss.astype(jnp.float32), metrics

    def _micro_grads(self, params, batch, rng, scaler):
        """One microbatch: scaled loss → grads (master-weight pattern: params are
        fp32, cast to compute dtype inside, so grads come back fp32)."""

        def scaled_loss(p):
            loss, metrics = self._loss_and_metrics(p, batch, rng)
            return scale_loss(loss, scaler), (loss, metrics)

        (_, (loss, metrics)), grads = jax.value_and_grad(
            scaled_loss, has_aux=True)(params)
        # fp32 grads regardless of param dtype (under offload the device
        # params are compute-dtype; the master update must not consume
        # precision-truncated grads)
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32)
            if jnp.issubdtype(g.dtype, jnp.floating) else g, grads)
        if self.grad_shardings is not None:
            grads = jax.lax.with_sharding_constraint(grads, self.grad_shardings)
        return loss, metrics, grads

    def _apply_grads(self, params, opt_state, scaler, grads, ok=None,
                     emit_health=False):
        """Unscale, overflow-check, update, conditional-skip (reference:
        ``FP16_Optimizer.step`` unscale/overflow path + ``_take_model_step``
        ``engine.py:2054``). Traced under the ``optimizer`` MFU region
        (``monitor/mfu.py``) so the step-time ledger can price the update
        phase separately from forward/backward.

        ``ok`` (optional traced bool) is the sentinel's in-graph health
        verdict: when given, the update is additionally gated on it — same
        discard semantics as an fp16 overflow, but WITHOUT touching the
        loss-scale state machine (a spiked-but-finite step is not an
        overflow). ``emit_health=True`` adds the sentinel's device-side
        scalars (``runtime/sentinel.py health_metrics``) to the return."""
        from ..monitor.mfu import region_scope

        with region_scope("optimizer"):
            return self._apply_grads_impl(params, opt_state, scaler, grads,
                                          ok=ok, emit_health=emit_health)

    def _apply_grads_impl(self, params, opt_state, scaler, grads, ok=None,
                          emit_health=False):
        grads = unscale_grads(grads, scaler)
        # the sentinel needs the nonfinite check even in pure-fp32 runs
        # (where fp16's overflow machinery would skip it)
        finite = grads_finite(grads) \
            if (self.fp16_enabled or ok is not None) else jnp.asarray(True)
        grad_norm = optax.global_norm(grads)
        clip = self.config.gradient_clipping
        if self._zeropp_enabled and clip and clip > 0:
            # zero++ removes optax's global-norm transform from the chain
            # (it would mis-compute inside shard_map); on this pjit/eager
            # path clip manually so the configured clipping still applies
            scale_f = jnp.minimum(1.0, clip / jnp.maximum(grad_norm, 1e-6))
            grads = jax.tree_util.tree_map(lambda g: g * scale_f, grads)

        health = {}
        if emit_health:
            # post-unscale: region norms must not wander with the dynamic
            # loss scale or the host z-score history is meaningless
            from .sentinel import health_metrics

            health = health_metrics(grads)
        gate = finite if ok is None else (finite & ok)
        new_params, new_opt, new_scaler = self._finish_update(
            params, opt_state, scaler, grads, finite, gate=gate)
        return new_params, new_opt, new_scaler, finite, grad_norm, health

    def _finish_update(self, params, opt_state, scaler, grads, finite,
                       gate=None):
        """Shared post-norm tail: optimizer update, overflow-skip revert,
        loss-scale bookkeeping. Used by the pjit/eager paths and the ZeRO++
        shard_map body — fp16 skip semantics live in exactly one place.

        ``gate`` (default: ``finite``) decides whether the update is
        *applied*; ``finite`` alone keeps driving the loss-scale state
        machine — a sentinel-gated skip must not burn hysteresis or reset
        the scale-growth window."""
        if gate is None:
            gate = finite
        updates, new_opt = self.optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)

        def pick(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(gate, n, o) if hasattr(n, "dtype") else n,
                new, old)

        new_params = pick(new_params, params)
        new_opt = pick(new_opt, opt_state)
        fp16 = self.config.fp16
        new_scaler = update_loss_scale(
            scaler, finite, dynamic=self.fp16_enabled and fp16.dynamic,
            scale_window=fp16.loss_scale_window, min_scale=fp16.min_loss_scale,
            hysteresis=fp16.hysteresis)
        return new_params, new_opt, new_scaler

    # ================================================================ fused path
    def _build_train_batch_fn(self):
        if self._zeropp_enabled:
            from .zeropp import build_zeropp_train_fn

            self._train_batch_raw = None  # explicit shard_map path
            if self.config.flops_profiler.enabled:
                logger.warning(
                    "flops_profiler is not available on the ZeRO++ explicit "
                    "shard_map path; profiling is disabled for this run")
            return build_zeropp_train_fn(self)
        gas = self.config.gradient_accumulation_steps

        def train_batch_fn(params, opt_state, scaler, batch, rng):
            # sentinel gate rider (runtime/sentinel.py): popped BEFORE the
            # accumulation scan (it is per-step, not per-microbatch — same
            # reason pld_theta is broadcast but this is not sliced)
            gate = None
            if isinstance(batch, dict) and SENTINEL_GATE_KEY in batch:
                batch = dict(batch)
                gate = batch.pop(SENTINEL_GATE_KEY)

            def micro(carry, mb):
                acc, i = carry
                loss, metrics, grads = self._micro_grads(
                    params, mb, jax.random.fold_in(rng, i), scaler)
                acc = jax.tree_util.tree_map(jnp.add, acc, grads)
                return (acc, i + 1), (loss, metrics)

            if self.grad_shardings is not None:
                zero_grads = jax.tree_util.tree_map(
                    lambda p, s: jax.lax.with_sharding_constraint(
                        jnp.zeros(p.shape, jnp.float32), s),
                    params, self.grad_shardings)
            else:
                zero_grads = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params)
            if gas == 1:
                loss, metrics, grads = self._micro_grads(params, batch, rng, scaler)
                losses = loss[None]
            else:
                (grads, _), (losses, metrics) = jax.lax.scan(
                    micro, (zero_grads, 0), batch)
                grads = jax.tree_util.tree_map(lambda g: g / gas, grads)
                metrics = jax.tree_util.tree_map(lambda m: m.mean(axis=0), metrics)
            ok = None
            if gate is not None:
                # in-graph health verdict: discard the update when the mean
                # loss clears the sentinel's cap. NaN compares False, so a
                # nonfinite loss is gated even before the host has history.
                ok = losses.mean() <= gate[0]
                # transient post-rollback LR cut (gate[1] is 1.0 otherwise —
                # an exact float no-op)
                grads = jax.tree_util.tree_map(lambda g: g * gate[1], grads)
            new_params, new_opt, new_scaler, finite, grad_norm, health = \
                self._apply_grads(params, opt_state, scaler, grads, ok=ok,
                                  emit_health=gate is not None)
            out_metrics = {
                **metrics,
                **health,
                "loss": losses.mean(),
                "grad_norm": grad_norm,
                "finite": finite,
                "loss_scale": new_scaler.scale,
            }
            return new_params, new_opt, new_scaler, out_metrics

        self._train_batch_raw = train_batch_fn  # unjitted, for the profiler
        return jax.jit(train_batch_fn, donate_argnums=(0, 1, 2))

    def train_batch(self, batch) -> Dict[str, Any]:
        """Full optimizer step on one *global* batch (leading dim =
        ``train_batch_size``; with accumulation the engine reshapes to
        ``(gas, step_batch, ...)`` and scans). The analog of the reference loop
        forward→backward→step and of ``PipelineEngine.train_batch``
        (``pipe/engine.py:321``)."""
        if self._sentinel is not None and self._sentinel.offer_batch():
            # journaled bad position being replayed (post-rollback or
            # post-restart): consume-and-discard BEFORE any dispatch. No
            # global_steps increment — the replayed trajectory keeps the
            # clean run's step numbering (and with it the per-step
            # fold_in(rng, global_steps) stream), which is what makes the
            # resumed losses float-identical to a run that never saw the
            # bad batch.
            return None
        if self.curriculum_scheduler is not None:
            # seqlen curriculum: clip the batch before compile — each
            # difficulty level is one compiled program (difficulty_step
            # bounds the number of levels)
            d = self.curriculum_scheduler.update_difficulty(self.global_steps)
            from .data_pipeline import truncate_to_difficulty

            batch = truncate_to_difficulty(batch, d)
        if self.random_ltd_scheduler is not None:
            v = self.random_ltd_scheduler.get_value(self.global_steps)
            if v != self._rltd_value:
                self._rltd_value = v
                self.module.config.random_ltd_current = v
                self._train_batch_fn = None  # retrace at the new keep count
        if self.qat_scheduler is not None:
            bits, changed = self.qat_scheduler.update(self.global_steps)
            if changed:
                self._qat_bits = bits
                # every cached program bakes the bits in: retrace them all
                self._train_batch_fn = None
                self._eval_fn = None
                self._grad_fn = None
        if self._train_batch_fn is None and self.offload_device is None:
            self._train_batch_fn = self._build_train_batch_fn()
        gas = self.config.gradient_accumulation_steps
        if gas > 1:
            batch = jax.tree_util.tree_map(
                lambda x: x.reshape((gas, x.shape[0] // gas) + x.shape[1:]), batch)
        if self.progressive_layer_drop is not None:
            # θ rides the batch as a traced scalar — it decays every step and
            # must never trigger a retrace (reference: PLD state dict merged
            # into the module kwargs, progressive_layer_drop.py get_state).
            # Injected AFTER the accumulation reshape: under gas>1 the scan
            # slices a (gas,) vector down to the per-microbatch scalar
            theta = self.progressive_layer_drop.update_state(self.global_steps)
            t = jnp.asarray(theta, jnp.float32)
            batch = {**batch,
                     "pld_theta": jnp.broadcast_to(t, (gas,)) if gas > 1
                     else t}
        if self._sentinel is not None and self.offload_device is None and \
                not self._zeropp_enabled and isinstance(batch, dict):
            # health-gate rider ([loss_cap, grad_scale], popped inside
            # train_batch_fn before the scan). Injected every armed step:
            # its PRESENCE changes the treedef (one retrace when arming),
            # its VALUES are data and retrace nothing.
            batch = {**batch,
                     SENTINEL_GATE_KEY: self._sentinel.gate_array()}
        if self._trace_cfg is not None and not self._tracing and \
                self.global_steps == int(self._trace_cfg.get("start_step", 1)):
            self.start_profile()
            self._trace_origin = "config"
        self.tput_timer.start()
        rng = jax.random.fold_in(self._rng, self.global_steps)
        fi = get_fault_injector()
        # this call executes what will be recorded as step global_steps+1
        # (the counter increments after dispatch): arm/hang stamps use that
        # number so they join exactly against the step span in the stream
        stepno = self.global_steps + 1
        if fi.armed:
            # rank-targeted comm-layer fault (utils/fault_injection.py): a
            # hang HERE is "this rank never arrives at the collective" —
            # siblings spin inside the all-reduce and only their watchdogs
            # (or the agent's teardown) end the pod
            fi.maybe_hang_step(self._fi_rank, stepno)
            # numerical fault (nan_step/loss_spike/bad_batch): poison the
            # data, not the riders — the sentinel must detect through its
            # own gate, and pld/gate scalars are engine state
            batch = fi.corrupt_batch(self._fi_rank, stepno, batch,
                                     skip_keys=("pld_theta",
                                                SENTINEL_GATE_KEY))
        if self._watchdog is not None:
            # pre-dispatch deadline stamp: the collective phase is armed
            # until the step's results are back (disarm in the finally
            # below — an exception mid-dispatch must not leave the deadline
            # live, or the watchdog would rc-218 the process ~deadline_s
            # later while the caller handles an ordinary error)
            self._watchdog.arm(stepno)
        # one-shot MFU trace window (telemetry.mfu): bracket EXACTLY this
        # step with a jax.profiler trace. Offload splits the step across
        # two programs and manual trace windows would nest — both skip.
        mfu_capture = False
        if self._mfu_pending and not self._tracing and \
                self.offload_device is None and \
                stepno >= self.config.telemetry.mfu_step:
            from ..monitor.telemetry import compile_stats

            self._mfu_compile_base = compile_stats()[0]
            try:
                # drain the async backlog FIRST: params are step N-1's
                # output, so waiting on one leaf retires every prior
                # step's device work — otherwise the window records their
                # tail and bills it into this step's regions
                jax.block_until_ready(  # dslint: allow(host-sync-in-step-path)
                    jax.tree_util.tree_leaves(self.params)[:1])
                jax.profiler.start_trace(self._mfu_trace_dir)
                mfu_capture = True
            except Exception as e:  # a broken profiler must not kill training
                logger.warning("mfu trace window failed to start: %s", e)
                self._mfu_pending = False
        t_step = time.perf_counter()
        try:
            if fi.armed:
                # phase="in": the rank ARRIVED (armed) and then wedged
                # inside its collective window — this rank's own watchdog
                # fires, exercising the self-abort half of the rc-218
                # contract
                fi.maybe_hang_step(self._fi_rank, stepno, phase="in")
            if self.offload_device is not None:
                metrics = self._offload_train_batch(batch, rng)
            else:
                # abstract avals (+ shardings) of EXACTLY this step's args —
                # curriculum truncation, gas reshape and pld_theta included —
                # so the compiled program can be re-lowered (a compile-cache
                # hit) for HLO-level comms accounting and graph_report
                # without holding the donated arrays. Avals only carry
                # shape/dtype/sharding, and params/opt/scaler keep theirs
                # across steps, so the full O(param-leaves) tree_map reruns
                # only when the batch/rng metadata actually changes
                # (curriculum truncation step, gas reshape) — not every step.
                key = (jax.tree_util.tree_structure((batch, rng)), tuple(
                    (jnp.shape(x), jnp.result_type(x),
                     getattr(x, "sharding", None))
                    for x in jax.tree_util.tree_leaves((batch, rng))))
                if key != getattr(self, "_last_aval_key", None) or \
                        getattr(self, "_last_train_avals", None) is None:
                    from ..analysis.capture import abstract_step_args

                    self._last_train_avals = abstract_step_args(
                        (self.params, self.opt_state, self.scaler_state,
                         batch, rng))
                    self._last_aval_key = key
                self.params, self.opt_state, self.scaler_state, metrics = \
                    self._train_batch_fn(self.params, self.opt_state,
                                         self.scaler_state, batch, rng)
            if comms_logger.enabled:
                # opt-in (comms_logger.enabled): straggler wall-clock must
                # be device-accurate, so this config knowingly trades the
                # overlap
                jax.block_until_ready(metrics["loss"])  # dslint: allow(host-sync-in-step-path)
                comms_logger.record_wall("train_batch",
                                         time.perf_counter() - t_step)
            elif self.telemetry is not None and self.telemetry.cfg.sync_timing:
                # telemetry.sync_timing: device-accurate step spans — trades
                # the dispatch/compute overlap for timing fidelity (see
                # on_step_end)
                jax.block_until_ready(metrics["loss"])  # dslint: allow(host-sync-in-step-path)
            # NOTE (watchdog + async dispatch): with neither sync knob on,
            # the jitted call can return before the device work runs, so a
            # purely device-side hang is caught when XLA's bounded
            # in-flight queue blocks a LATER dispatch — still inside an
            # armed window, so rc-218 fires, but attribution may name a
            # step a few later than the wedged one. telemetry.sync_timing
            # opts into device-accurate (exact-step) windows at the
            # documented cost of the dispatch/compute overlap.
        finally:
            if self._watchdog is not None:
                # post-dispatch: the step span recorded in on_step_end
                # below is the durable post record the pod report joins
                self._watchdog.disarm(stepno)
            if mfu_capture and sys.exc_info()[0] is not None:
                # exception mid-dispatch: close the profiler session so a
                # caller that survives the error can still trace later
                try:
                    jax.profiler.stop_trace()
                except Exception:  # pragma: no cover - defensive
                    pass
                mfu_capture = False
        step_dur = time.perf_counter() - t_step
        if mfu_capture:
            # sync + close the window; the synced wall is the ledger's
            # clean-step time (one deliberately-blocking step)
            step_dur = self._finish_mfu_window(stepno, t_step, metrics)
        self.global_steps += 1
        self.micro_steps += gas
        if self.telemetry is not None:
            # step span + recompile attribution + goodput + heartbeat +
            # periodic HBM gauges — a few host dict appends (<5% guarded by
            # tests/unit/test_telemetry.py::test_telemetry_overhead)
            self.telemetry.on_step_end(self.global_steps, step_dur,
                                       batch=batch,
                                       offload=self._pop_offload_stats())
        if self._tracing and self._trace_origin == "config":
            start = int(self._trace_cfg.get("start_step", 1))
            n = int(self._trace_cfg.get("num_steps", 3))
            # close INSIDE the last in-window call — a loop that ends with
            # the window would otherwise exit with the trace open and no
            # artifacts written
            if self.global_steps >= start + n:
                self.stop_profile()
        if (self.config.flops_profiler.enabled and self.offload_device is None
                and getattr(self, "_train_batch_raw", None) is not None):
            # post-donation the old state is gone; new state has identical
            # shapes, which is all static FLOP analysis needs
            self.flops_profiler.maybe_profile(
                self._train_batch_raw,
                (self.params, self.opt_state, self.scaler_state, batch, rng))
        self._post_step(metrics)
        if fi.armed:
            rc = fi.should_kill(self._fi_rank, self.global_steps)
            if rc is not None:
                # a hard crash, not a preemption: no emergency save, no
                # cleanup — the elastic agent's prompt-teardown path is
                # what this fault exists to exercise
                logger.error("fault injection: rank %d dying with rc=%d "
                             "after step %d", self._fi_rank, rc,
                             self.global_steps)
                if self.telemetry is not None:
                    try:
                        self.telemetry.dump("injected_kill")
                    except Exception:
                        pass
                os._exit(rc)
        return metrics

    def start_profile(self, trace_dir: Optional[str] = None) -> None:
        """Begin an XLA device-timeline capture (jax.profiler trace —
        TensorBoard/Perfetto-viewable; the role NVTX ranges + nsys play for
        the reference). Also usable manually around any region."""
        if self._tracing:
            return
        trace_dir = trace_dir or (self._trace_cfg or {}).get(
            "trace_dir") or os.path.join(os.getcwd(), "dstpu_traces")
        jax.profiler.start_trace(trace_dir)
        self._tracing = True
        self._trace_origin = "manual"  # train_batch overrides for windows
        import atexit

        atexit.register(self.stop_profile)  # never exit with an open trace
        log_dist(f"jax.profiler trace started -> {trace_dir}")

    def stop_profile(self) -> None:
        if not self._tracing:
            return
        jax.block_until_ready(jax.tree_util.tree_leaves(self.params)[:1])
        jax.profiler.stop_trace()
        self._tracing = False
        self._trace_origin = None
        log_dist("jax.profiler trace stopped")

    def xla_comms_summary(self, log: bool = True,
                          show_straggler: bool = False) -> Dict[str, Dict]:
        """Post-compile accounting of the collectives XLA's partitioner
        inserted into the fused train step — the traffic the façade logger
        can never see (VERDICT r3 #6; reference ``log_summary`` via
        ``comm/comm.py:422``). Re-lowers the train program at the last
        step's avals (a compile-cache hit), parses the optimized HLO, and
        merges per-opcode byte totals into ``comms_logger``."""
        if not comms_logger.enabled or \
                getattr(self, "_last_train_avals", None) is None:
            # avals are captured on every step now, but the summary merges
            # into comms_logger state — without the logger it has nowhere
            # to land (use graph_report() for logger-free analysis)
            raise RuntimeError(
                "run train_batch() with comms_logger enabled first "
                "(config comms_logger.enabled: true)")
        from ..comm.hlo_comms import summarize_compiled

        compiled = self._train_batch_fn.lower(
            *self._last_train_avals).compile()
        summary = summarize_compiled(compiled)
        comms_logger.record_hlo(summary, tag="train_step")
        if log:
            comms_logger.log_summary(show_straggler=show_straggler)
        return summary

    def emit_comm_census(self) -> Dict[str, Any]:
        """Classify the compiled train step's collectives into traffic
        classes (``analysis/collectives.py``) and persist the class summary
        as a ``comm/census`` flight-recorder event — the static half of the
        pod report's bytes/time/bandwidth join (``monitor/pod.py``). Also
        records the raw per-opcode mix into ``comms_logger`` (when enabled)
        so a ``comm/snapshot`` lands beside it on the next dump, giving the
        offline join its measured cross-check. Returns the payload."""
        report = self.graph_report(analyzers=("collectives",))
        payload: Dict[str, Any] = {
            "classes": report["collectives"].classes.summary(),
            "group_size": report["collectives"].expectation.group_size,
            "n_devices": int(np.prod(list(self.topology.axis_sizes.values()))),
            "zero_stage": self.zero_stage,
        }
        if comms_logger.enabled:
            # merge the measured op mix from the same compiled program into
            # comms_logger (xla:: keys) so the next dump's comm/snapshot
            # carries it
            self.xla_comms_summary(log=False)
        if self.telemetry is not None:
            self.telemetry.record_census(payload)
        return payload

    GRAPH_ANALYZERS = ("collectives", "donation", "resharding", "dtype")

    def graph_report(self, gathers_per_param: Optional[int] = None,
                     analyzers: Tuple[str, ...] = GRAPH_ANALYZERS,
                     ) -> Dict[str, Any]:
        """Static analysis of the compiled train step (``analysis/``):
        collective census vs the analytic parallelism expectation, donation
        audit, activation dtype audit and resharding detection.

        Audits EXACTLY the program the last ``train_batch`` ran, from the
        avals captured at its call site (re-lowering is a compile-cache
        hit). ``analyzers`` selects a subset — the dtype audit re-traces
        the raw step with ``make_jaxpr``, which a caller that only wants
        the donation report (the bench) should not pay for.

        ``gathers_per_param`` defaults from this engine's own remat config
        (2 when activation checkpointing is on — backward may legally
        re-gather each ZeRO-3 param — else 1); the analytic budget must
        not flag a correct remat graph. XLA often hoists the gather out
        of the remat region anyway, and ``exact=False`` treats the
        expectation as a ceiling, so 2 stays sound either way.
        """
        if gathers_per_param is None:
            ac = "activation_checkpointing" in self.config.raw and \
                self.config.activation_checkpointing.enabled
            gathers_per_param = 2 if ac else 1
        from ..analysis import (check_collectives, collective_census,
                                donation_audit, dtype_audit,
                                expected_train_collectives, resharding_audit)

        if self.offload_device is not None:
            raise RuntimeError(
                "graph_report audits the fused train step; the offload path "
                "splits the step into a grads fn + host apply — audit those "
                "directly with the analysis.* functions")
        avals = getattr(self, "_last_train_avals", None)
        if self._train_batch_fn is None or avals is None:
            raise RuntimeError("run train_batch() first")
        compiled = self._train_batch_fn.lower(*avals).compile()
        report: Dict[str, Any] = {}
        if "collectives" in analyzers or "resharding" in analyzers:
            report["census"] = collective_census(compiled)
        if "collectives" in analyzers:
            expectation = expected_train_collectives(
                avals[0], self.topology, self.zero_stage,
                param_shardings=self.param_shardings,
                gathers_per_param=gathers_per_param)
            report["collectives"] = check_collectives(
                report["census"], expectation, avals[0],
                self.param_shardings, exact=False)
        if "donation" in analyzers:
            report["donation"] = donation_audit(compiled, avals,
                                                donate_argnums=(0, 1, 2))
        if "resharding" in analyzers:
            report["resharding"] = resharding_audit(
                compiled, params=avals[0],
                param_shardings=self.param_shardings,
                census=report["census"])
        if "dtype" in analyzers:
            param_shapes = [tuple(np.shape(p))
                            for p in jax.tree_util.tree_leaves(avals[0])]
            report["dtype"] = dtype_audit(
                jax.make_jaxpr(self._train_batch_raw)(*avals)
                if getattr(self, "_train_batch_raw", None) is not None else
                jax.make_jaxpr(lambda *a: self._train_batch_fn(*a))(*avals),
                allowed_shapes=param_shapes)
        return report

    # ================================================================ mfu
    def _finish_mfu_window(self, stepno: int, t_step: float,
                           metrics: Dict[str, Any]) -> float:
        """Close the one-shot MFU trace window: block on the step's result
        (the window's step wall must be device-accurate — this is the one
        deliberately-synced step), stop the trace, and keep the window only
        if the step compiled nothing (a compile inside the window is not a
        clean step; re-arm for a later one, bounded). Returns the synced
        step duration so goodput accounts the real wall either way."""
        try:
            jax.block_until_ready(metrics["loss"])  # dslint: allow(host-sync-in-step-path)
        finally:
            dur = time.perf_counter() - t_step
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # pragma: no cover - defensive
                logger.warning("mfu trace window failed to stop: %s", e)
                self._mfu_pending = False
                return dur
        from ..monitor.telemetry import compile_stats

        self._mfu_attempts += 1
        if compile_stats()[0] - self._mfu_compile_base > 0:
            if self._mfu_attempts >= 5:
                self._mfu_pending = False
                logger.warning(
                    "mfu window: no clean (non-compiling) step within 5 "
                    "attempts — shape thrash? see Compile/* events; giving "
                    "up on the ledger capture")
            return dur
        self._mfu_pending = False
        self._mfu_window = {"step": stepno, "step_s": dur, "steps": 1,
                            "trace_dir": self._mfu_trace_dir}
        if self.telemetry is not None:
            self.telemetry.recorder.record(
                "event", "mfu/window", step=stepno,
                data={"step_s": dur, "steps": 1,
                      "trace_dir": self._mfu_trace_dir})
        return dur

    def mfu_ledger(self, spec: Any = None, persist: bool = True
                   ) -> Dict[str, Any]:
        """The step-time attribution ledger (docs/observability.md "MFU
        ledger"): joins (1) the roofline partition of the compiled step's
        jaxpr into named regions (``analysis/roofline.py`` — analytic
        FLOPs / HBM bytes / comm bytes per ``mfu.*`` scope, priced against
        the device peak-spec registry), (2) the measured per-op times of
        the captured clean-step trace window grouped by region via the
        named_scope metadata XLA stamped into the compiled HLO
        (``monitor/mfu.py``), and (3) the HLO collective census
        (partitioner-inserted traffic the jaxpr can't see). Emits the
        strict ``MFU/*`` event family, persists the offline artifacts
        (opmap/roofline/window/ledger JSON next to the trace, the
        ``tools/mfu_report.py`` contract) and returns the ledger dict.

        Requires a captured window (``telemetry.mfu``) and the fused train
        path — the ZeRO++ explicit step has no retraceable raw fn, and
        offload splits the step across two programs."""
        from ..analysis import collective_census, roofline
        from ..monitor import mfu as mfu_mod

        if self._mfu_window is None:
            raise RuntimeError(
                "no MFU trace window captured — enable telemetry.mfu "
                '({"telemetry": {"enabled": true, "mfu": {"enabled": '
                'true}}}) and run past telemetry.mfu.step clean steps')
        if self._train_batch_fn is None or \
                getattr(self, "_last_train_avals", None) is None or \
                getattr(self, "_train_batch_raw", None) is None:
            raise RuntimeError(
                "mfu_ledger audits the fused train step — run train_batch"
                "() first (ZeRO++ explicit-shard_map and offload split "
                "steps are not supported)")
        avals = self._last_train_avals
        compiled = self._train_batch_fn.lower(*avals).compile()
        opmap = mfu_mod.build_opmap(compiled.as_text())
        costs = roofline.region_costs(
            jax.make_jaxpr(self._train_batch_raw)(*avals))
        census_bytes = sum(e["bytes"] for e in collective_census(compiled))
        spec = spec or roofline.device_spec()
        table = roofline.roofline_table(costs, spec,
                                        census_bytes=census_bytes)
        w = self._mfu_window
        trace_path = mfu_mod.find_trace(w["trace_dir"])
        if trace_path is None:
            raise RuntimeError(f"no trace file under {w['trace_dir']} — "
                               f"profiler produced no artifacts")
        events, meta = mfu_mod.parse_trace(trace_path)
        measured = mfu_mod.measure_regions(events, opmap,
                                           steps=w.get("steps", 1))
        led = mfu_mod.ledger(table, measured, w["step_s"],
                             truncated_trace=meta["truncated"])
        led["window"] = {"step": w["step"], "trace_path": trace_path}
        if persist:
            # the offline-report artifacts (tools/mfu_report.py reads the
            # trace dir on a jax-less node)
            for fname, payload in (("mfu_opmap.json", opmap),
                                   ("mfu_roofline.json", table),
                                   ("mfu_window.json", w),
                                   ("mfu_ledger.json", led)):
                try:
                    with open(os.path.join(w["trace_dir"], fname),
                              "w") as f:
                        json.dump(payload, f)
                except (OSError, TypeError, ValueError) as e:
                    logger.warning("mfu artifact %s not written: %s",
                                   fname, e)
        if self.telemetry is not None:
            self.telemetry.recorder.record(
                "event", "mfu/ledger", step=w["step"],
                data={k: led[k] for k in
                      ("achieved_mfu", "roofline_mfu", "step_s",
                       "device_busy_s", "top_sinks")})
        if self.monitor.enabled:
            from ..monitor.telemetry import check_events

            self.monitor.write_events(
                check_events(mfu_mod.ledger_events(led, step=w["step"])))
        return led

    # ================================================================ eager path
    def forward(self, batch):
        """Loss-only forward (reference ``engine.forward:1781``); caches the batch
        for the subsequent :meth:`backward`."""
        if self._eval_fn is None:
            self._eval_fn = jax.jit(
                lambda p, b, r: self._loss_and_metrics(p, b, r,
                                                       train=False)[0])
        self.timers(FORWARD_GLOBAL_TIMER).start()
        self._last_batch = batch
        loss = self._eval_fn(self.params, batch,
                             jax.random.fold_in(self._rng, self.micro_steps))
        self.timers(FORWARD_GLOBAL_TIMER).stop()
        self.losses = loss
        return loss

    def backward(self, loss=None, batch=None):
        """Accumulate gradients for one microbatch (reference ``engine.backward:
        1922``). JAX has no stored autograd graph, so grads are recomputed from the
        cached (or given) batch; the ``loss`` argument is accepted for loop parity
        and ignored."""
        if self._grad_fn is None:
            self._grad_fn = jax.jit(
                lambda p, b, r, s: self._micro_grads(p, b, r, s))
            # once per run: ported reference loops land here and silently
            # pay ~2x FLOPs (JAX has no stored autograd graph, so backward
            # recomputes the forward) — point them at the fused path
            logger.warning(
                "eager forward()/backward()/step() loop detected: backward "
                "recomputes the forward under JAX (~2x FLOPs). Prefer "
                "engine.train_batch(batch) — one fused jitted step with "
                "identical semantics (see docs/MIGRATING.md)")
        batch = batch if batch is not None else self._last_batch
        if batch is None:
            raise RuntimeError("backward() needs forward() first or an explicit batch")
        self.timers(BACKWARD_GLOBAL_TIMER).start()
        repl = self.topology.replicated()
        rng = jax.device_put(jax.random.fold_in(self._rng, self.micro_steps),
                             repl)
        # under offload the scaler lives host-side between steps
        scaler = jax.device_put(self.scaler_state, repl)
        loss_val, _, grads = self._grad_fn(self.params, batch, rng, scaler)
        if self._accum_grads is None:
            self._accum_grads = grads
        else:
            self._accum_grads = jax.tree_util.tree_map(jnp.add, self._accum_grads,
                                                       grads)
        self._accum_losses.append(loss_val)
        self._accum_count += 1
        self.micro_steps += 1
        self.timers(BACKWARD_GLOBAL_TIMER).stop()
        return loss_val

    def is_gradient_accumulation_boundary(self) -> bool:
        """Reference ``engine.is_gradient_accumulation_boundary``."""
        return self._accum_count >= self.config.gradient_accumulation_steps

    def step(self):
        """Apply accumulated gradients (reference ``engine.step:2120`` →
        ``_take_model_step:2054``)."""
        if self._accum_grads is None:
            raise RuntimeError("step() before backward()")
        if self.offload_device is not None:
            self.timers(STEP_GLOBAL_TIMER).start()
            grads = jax.tree_util.tree_map(
                lambda g: g / float(self._accum_count), self._accum_grads)
            metrics = dict(self._host_step(grads))
            self.timers(STEP_GLOBAL_TIMER).stop()
            if self._accum_losses:
                metrics["loss"] = jnp.stack(self._accum_losses).mean()
            self._accum_grads, self._accum_count = None, 0
            self._accum_losses = []
            self.global_steps += 1
            if self.telemetry is not None:
                # eager-path step span: boundary-to-boundary wall (dur=None)
                self.telemetry.on_step_end(
                    self.global_steps, offload=self._pop_offload_stats())
            self._post_step(metrics)
            return metrics
        if self._apply_fn is None:
            def apply_fn(params, opt_state, scaler, grads, count):
                grads = jax.tree_util.tree_map(lambda g: g / count, grads)
                new_params, new_opt, new_scaler, finite, grad_norm, _ = \
                    self._apply_grads(params, opt_state, scaler, grads)
                return new_params, new_opt, new_scaler, {
                    "finite": finite, "grad_norm": grad_norm,
                    "loss_scale": new_scaler.scale}
            # grads donate too (donation-audit find): the accumulator is
            # dead after this call (_accum_grads is cleared below), and an
            # undonated fp32 grad tree is a full extra param-sized buffer
            self._apply_fn = jax.jit(apply_fn, donate_argnums=(0, 1, 2, 3))
        self.timers(STEP_GLOBAL_TIMER).start()
        self.params, self.opt_state, self.scaler_state, metrics = self._apply_fn(
            self.params, self.opt_state, self.scaler_state, self._accum_grads,
            float(self._accum_count))
        self.timers(STEP_GLOBAL_TIMER).stop()
        metrics = dict(metrics)
        if self._accum_losses:
            # mean over the accumulation window (matches the fused path's
            # losses.mean(), not just the last microbatch)
            metrics["loss"] = jnp.stack(self._accum_losses).mean()
        self._accum_grads = None
        self._accum_count = 0
        self._accum_losses = []
        self.global_steps += 1
        if self.telemetry is not None:
            # eager-path step span: boundary-to-boundary wall (dur=None) —
            # includes data/host time between steps, unlike the fused path's
            # measured step_dur
            self.telemetry.on_step_end(self.global_steps)
        self._post_step(metrics)
        return metrics

    def _pop_offload_stats(self) -> Optional[Dict[str, Any]]:
        """The offload pipeline's per-step ledger, consumed exactly once."""
        stats = getattr(self, "_last_offload_stats", None)
        self._last_offload_stats = None
        return stats

    # ================================================================ shared tail
    def _post_step(self, metrics: Dict[str, Any]):
        """Per-step host bookkeeping. Deliberately does NOT force a device sync:
        metric arrays are only pulled at print boundaries so host dispatch of step
        n+1 overlaps device compute of step n (the reference gets the same overlap
        from streams; blocking here would serialize the pipeline)."""
        self.tput_timer.stop(report_speed=True)
        if self.global_steps % self.config.steps_per_print == 0:
            if self.fp16_enabled and not bool(
                    np.asarray(jax.device_get(metrics["finite"]))):
                log_dist(f"overflow: skipped step {self.global_steps}, "
                         f"loss scale -> {self.get_loss_scale()}")
            loss = metrics.get("loss")
            log_dist(
                f"step={self.global_steps} "
                f"loss={float(jax.device_get(loss)) if loss is not None else float('nan'):.4f} "
                f"lr={self.get_lr():.3e} scale={self.get_loss_scale():.1f}")
        if self.monitor.enabled:
            # Buffer device scalars; device_get only at print boundaries so the
            # host never blocks on in-flight steps (reference gets the same
            # overlap from CUDA streams).
            samples = self.global_steps * self.config.train_batch_size
            ev = [("Train/Samples/train_loss", metrics["loss"], samples)
                  ] if "loss" in metrics else []
            ev.append(("Train/Samples/lr", ("__lr__", self.global_steps), samples))
            if self.fp16_enabled:
                ev.append(("Train/Samples/loss_scale", metrics["loss_scale"],
                           samples))
            self._pending_events.extend(ev)
            if self.global_steps % self.config.steps_per_print == 0:
                self._flush_monitor()
        if self.config.wall_clock_breakdown and \
                self.global_steps % self.config.steps_per_print == 0:
            self.timers.log([FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                             STEP_GLOBAL_TIMER])
        if self._sentinel is not None:
            # lag-deferred health verdicts (runtime/sentinel.py): enqueue
            # this step's device scalars; entries >= cfg.lag steps old have
            # retired on device, so their pull is not a pipeline stall
            self._sentinel.at_step_boundary(self.global_steps, metrics)
        if self._resilience is not None:
            # step boundary: the only point where every buffer is quiescent,
            # so a pending SIGTERM (or injected preemption) saves here
            self._resilience.at_step_boundary()

    def _flush_monitor(self):
        events = []
        for name, val, samples in self._pending_events:
            if isinstance(val, tuple) and val[0] == "__lr__":
                try:
                    val = self.lr_schedule(val[1])
                except TypeError:
                    val = self.get_lr()
            events.append((name, float(jax.device_get(val)), samples))
        self._pending_events = []
        # degradation visibility: surface changed resilience counters (I/O
        # retries, fallback loads, emergency saves, …) as monitor events so
        # operators see trouble brewing instead of discovering it at recovery
        from ..monitor.monitor import resilience_counters

        samples = self.global_steps * self.config.train_batch_size
        for name, value in resilience_counters.snapshot().items():
            if value and value != self._resilience_reported.get(name):
                self._resilience_reported[name] = value
                events.append((f"Resilience/{name}", value, samples))
        if self.telemetry is not None:
            # Goodput/*, Memory/*, Compile/*, Ckpt/* at every print boundary
            events.extend(self.telemetry.periodic_events(samples))
        if comms_logger.enabled:
            events.extend(comms_logger.summary_events(samples))
        if events:
            self.monitor.write_events(events)

    # ================================================================ accessors
    @property
    def skipped_steps(self) -> int:
        """Cumulative overflow-skipped steps, tracked on-device by the loss
        scaler (reads force a sync; use sparingly)."""
        return int(jax.device_get(self.scaler_state.overflows))

    def get_lr(self) -> float:
        lr = current_lr(self.opt_state)
        if lr is None:
            try:
                lr = self.lr_schedule(self.global_steps)
            except TypeError:
                return float("nan")
        return float(jax.device_get(lr))

    def get_loss_scale(self) -> float:
        return float(jax.device_get(self.scaler_state.scale))

    def get_global_grad_norm(self) -> Optional[float]:
        return None  # exposed per-step in train metrics

    def train_micro_batch_size_per_gpu(self) -> int:
        return self.config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self) -> int:
        return self.config.gradient_accumulation_steps

    def train_batch_size(self) -> int:
        return self.config.train_batch_size

    def register_dataloader(self, loader):
        """Attach the loader feeding ``train_batch`` so its iterator state
        (epoch/offset/seed — ``dataloader.state_dict``) rides checkpoint
        meta: resumes continue the stream instead of silently replaying or
        skipping data, and the sentinel's rollback can rewind it.
        ``initialize()`` registers the loader it builds automatically."""
        self._dataloader = loader
        return loader

    # ================================================================ resilience
    def enable_preemption_handling(self, save_dir: str,
                                   install_signal_handlers: bool = True,
                                   exit_fn: Optional[Callable[[int], None]]
                                   = None):
        """Arm preemption-aware checkpointing: SIGTERM/SIGINT (or an injected
        ``preempt_at_step`` fault) triggers an emergency ``save_checkpoint``
        into ``save_dir`` at the next step boundary, then exits with
        ``resilience.PREEMPTION_EXIT_CODE`` — which the elastic agent treats
        as a free restart. Returns the installed
        :class:`~.resilience.ResilienceManager`."""
        from .resilience import ResilienceManager

        self._resilience = ResilienceManager(self, save_dir, exit_fn=exit_fn)
        if install_signal_handlers:
            self._resilience.install()
        return self._resilience

    # ================================================================ checkpoint
    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[Dict] = None,
                        save_latest: bool = True) -> str:
        """Sharded checkpoint save (reference ``engine.save_checkpoint:3050``:
        mp-rank module files + per-DP-rank ZeRO shards + ``latest`` tag file —
        here one orbax sharded tree serves all topologies), through the
        configured checkpoint engine (sync native, or the async Nebula-analog
        that returns after the host snapshot)."""
        if self.telemetry is not None:
            with self.telemetry.ckpt_span("save", step=self.global_steps):
                return self._save_checkpoint_impl(save_dir, tag, client_state,
                                                  save_latest)
        return self._save_checkpoint_impl(save_dir, tag, client_state,
                                          save_latest)

    def _save_checkpoint_impl(self, save_dir: str, tag: Optional[str],
                              client_state: Optional[Dict],
                              save_latest: bool) -> str:
        tag = tag or f"global_step{self.global_steps}"
        self._validate_tag(tag)
        path = os.path.join(save_dir, tag)
        if self._mh_offload is not None:
            # per-host master/moment shards reassemble into global arrays;
            # orbax writes them multi-controller like any sharded tree
            state = {"params": self._mh_offload.master_global_tree(),
                     "opt_state": self._mh_offload.moments_global_tree(),
                     "scaler": self.scaler_state}
        elif self.offload_device is not None:
            # persist the fp32 master copy (device params are lossy bf16)
            if self._swapper is not None and self.opt_state is None:
                self._swap_in_opt_state()
            state = {"params": self.master_params, "opt_state": self.opt_state,
                     "scaler": self.scaler_state}
        else:
            state = {"params": self.params, "opt_state": self.opt_state,
                     "scaler": self.scaler_state}
        meta = {"global_steps": self.global_steps, "micro_steps": self.micro_steps,
                "skipped_steps": self.skipped_steps,
                "config": {"zero_stage": self.zero_stage},
                "client_state": client_state or {}}
        if self.curriculum_scheduler is not None:
            meta["curriculum"] = self.curriculum_scheduler.state_dict()
        if self.random_ltd_scheduler is not None:
            meta["random_ltd"] = self.random_ltd_scheduler.state_dict()
        if self.qat_scheduler is not None:
            meta["qat"] = self.qat_scheduler.state_dict()
        if self._dataloader is not None and \
                hasattr(self._dataloader, "state_dict"):
            # iterator position rides the meta: a resume continues the data
            # stream where this save left it (and the sentinel's rollback
            # rewinds it deterministically)
            meta["dataloader"] = self._dataloader.state_dict()
        if self._sentinel is not None:
            meta["sentinel"] = self._sentinel.state_dict()
        post_commit = None
        keep = self.config.checkpoint.keep_last_n
        if keep and self._fi_rank == 0:
            from ..checkpoint.engine import rotate_checkpoints

            # rotation rides the engine's post-commit hook so it only ever
            # runs once the new tag is durable (async: on the worker thread)
            post_commit = lambda: rotate_checkpoints(save_dir, keep)  # noqa: E731
        self.checkpoint_engine.save(
            path, state, meta,
            latest_file=(os.path.join(save_dir, LATEST_FILE)
                         if save_latest else None),
            tag=tag, post_commit=post_commit)
        if self._swapper is not None:
            self._swap_out_opt_state()
        if self._sentinel is not None:
            # the tag enters the last-good promotion queue; it is promoted
            # only once K healthy steps beyond it are observed
            self._sentinel.note_checkpoint(tag, self.global_steps, save_dir)
        log_dist(f"saved checkpoint {path} "
                 f"({self.checkpoint_engine.name} engine)")
        return path

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_optimizer_states: bool = True
                        ) -> Tuple[Optional[str], Dict]:
        """Restore (reference ``engine.load_checkpoint:2688``). Resharding-on-load:
        orbax restores into the *current* shardings, so a checkpoint written on any
        topology loads on any other — the capability the reference needs universal
        checkpoints for.

        A tag that passes :func:`~..checkpoint.engine.verify_tree` but tears
        between verification and read (raising
        ``CheckpointCorruptionError``) is quarantined and resolution retried
        on the remaining history — the engine path recovers from the same
        verified-then-torn race :func:`~..checkpoint.engine.load_latest_valid`
        does. An explicitly requested ``tag`` is never walked past: its
        corruption propagates to the caller."""
        from ..checkpoint.engine import (CheckpointCorruptionError,
                                         quarantine_tag)

        while True:
            try:
                return self._load_checkpoint_once(load_dir, tag,
                                                  load_optimizer_states)
            except CheckpointCorruptionError as e:
                if tag is not None:
                    raise
                from ..monitor.monitor import resilience_counters

                logger.warning("checkpoint %s corrupt on read (%s); "
                               "quarantining and retrying resolution",
                               e.path, e.reason)
                resilience_counters.incr("corrupt_tags_skipped")
                quarantine_tag(e.path)

    def _load_checkpoint_once(self, load_dir: str, tag: Optional[str],
                              load_optimizer_states: bool
                              ) -> Tuple[Optional[str], Dict]:
        load_tree = self.checkpoint_engine.load
        # before resolving `latest`: an async save may still be writing it
        self.checkpoint_engine.wait()
        if self._fi_rank == 0:
            # a worker killed mid-save before this restart left .staging-*
            # orphans (and possibly a torn-pod tag) behind; resume is the
            # natural sweep point, and pod rank 0 owns shared-dir hygiene
            from ..checkpoint.ckpt_engine import sweep_staging_dirs

            sweep_staging_dirs(load_dir)
        if tag is None:
            tag = self._resolve_resume_tag(load_dir)
            if tag is None:
                return None, {}
        path = os.path.join(load_dir, tag)
        if glob_mod.glob(os.path.join(path, "mp_rank_*_model_states.pt")):
            # a REFERENCE-format checkpoint (torch .pt layout): route to the
            # importer so DeepSpeed users' existing checkpoints just load
            from ..checkpoint.ds_import import load_deepspeed_checkpoint

            got = load_deepspeed_checkpoint(
                self, load_dir, tag,
                load_optimizer_states=load_optimizer_states)
            return os.path.join(load_dir, got), {}
        repl = self.topology.replicated()
        scaler_sh = jax.tree_util.tree_map(lambda _: repl, self.scaler_state)
        if self._mh_offload is not None:
            mh = self._mh_offload
            # shape-only template — moments_global_tree() would read the
            # whole optimizer state off NVMe just to learn shapes
            mom = mh.moments_template_tree()
            template = {
                "params": (mh.master_global_tree(), mh.shard_shardings),
                "opt_state": (mom, {"m": mh.shard_shardings,
                                    "v": mh.shard_shardings,
                                    "step": repl}),
                "scaler": (self.scaler_state, scaler_sh)}
            state, meta = load_tree(path, template)
            mh.load_state(state["params"],
                          state["opt_state"] if load_optimizer_states
                          else None)
            if load_optimizer_states:
                # back to host-numpy residence (see _init_offload): the
                # restore device_put the scaler to the mesh like any leaf
                from .loss_scaler import host_loss_scale_state

                self.scaler_state = host_loss_scale_state(state["scaler"])
            self.params = self._mh_push(mh.master_global_tree())
        elif self.offload_device is not None:
            if self._swapper is not None and self.opt_state is None:
                self._swap_in_opt_state()  # template needs the live tree
            cpu = self._cpu_device
            template = {"params": (self.master_params,
                                   jax.tree_util.tree_map(lambda _: cpu,
                                                          self.master_params)),
                        "opt_state": (self.opt_state,
                                      jax.tree_util.tree_map(lambda _: cpu,
                                                             self.opt_state)),
                        "scaler": (self.scaler_state, scaler_sh)}
            state, meta = load_tree(path, template)
            self.master_params = state["params"]
            if load_optimizer_states:
                self.opt_state = state["opt_state"]
                self.scaler_state = state["scaler"]
            self.params = self._push_params_to_device(self.master_params)
            if self._swapper is not None:
                self._swap_out_opt_state()
        else:
            template = {"params": (self.params, self.param_shardings),
                        "opt_state": (self.opt_state, self.opt_shardings),
                        "scaler": (self.scaler_state, scaler_sh)}
            state, meta = load_tree(path, template)
            self.params = state["params"]
            if load_optimizer_states:
                self.opt_state = state["opt_state"]
                self.scaler_state = state["scaler"]
        self.global_steps = meta.get("global_steps", 0)
        self.micro_steps = meta.get("micro_steps", 0)
        if self.curriculum_scheduler is not None and "curriculum" in meta:
            self.curriculum_scheduler.load_state_dict(meta["curriculum"])
        if self.random_ltd_scheduler is not None and "random_ltd" in meta:
            self.random_ltd_scheduler.load_state_dict(meta["random_ltd"])
        if self.qat_scheduler is not None and "qat" in meta:
            self.qat_scheduler.load_state_dict(meta["qat"])
            self._qat_bits, _ = self.qat_scheduler.update(self.global_steps)
            self._train_batch_fn = None  # retrace at the restored precision
            self._eval_fn = None
            self._grad_fn = None
        if self._dataloader is not None and "dataloader" in meta and \
                hasattr(self._dataloader, "load_state_dict"):
            self._dataloader.load_state_dict(meta["dataloader"])
        if self._sentinel is not None and "sentinel" in meta:
            self._sentinel.load_state_dict(meta["sentinel"])
        # skipped_steps rides in scaler_state.overflows, restored above
        log_dist(f"loaded checkpoint {path}")
        return path, meta.get("client_state", {})

    def _resolve_resume_tag(self, load_dir: str) -> Optional[str]:
        """Which tag to resume from: whatever ``latest`` names if it
        verifies, else the newest tag in history that does — a torn newest
        checkpoint costs one save interval, not the run. ``None`` when the
        directory holds nothing loadable.

        Shallow verification only (meta/index parse + file sizes): the
        chosen tag is immediately read by ``load_tree``, which checks every
        leaf's crc32 and raises ``CheckpointCorruptionError`` on mismatch —
        deep-verifying here would stream a multi-GB checkpoint twice on the
        restart critical path."""
        from ..checkpoint.engine import _read_latest, find_latest_valid_tag
        from ..monitor.monitor import resilience_counters

        pointed = _read_latest(load_dir)
        if pointed is not None and glob_mod.glob(
                os.path.join(load_dir, pointed, "mp_rank_*_model_states.pt")):
            # a REFERENCE-format (torch .pt layout) checkpoint carries no
            # dstpu manifest to verify; hand it to the importer untouched
            return self._agree_resume_tag(pointed)
        tag, skipped = find_latest_valid_tag(load_dir, deep=False)
        for skipped_tag, reason in skipped:
            logger.warning("skipping corrupt checkpoint %s: %s",
                           os.path.join(load_dir, skipped_tag), reason)
            resilience_counters.incr("corrupt_tags_skipped")
        tag = self._agree_resume_tag(tag)
        if tag is None:
            logger.warning("no loadable checkpoint in %s; nothing loaded",
                           load_dir)
            return None
        if tag != pointed or skipped:
            resilience_counters.incr("fallback_loads")
            logger.warning("fallback load: resuming %s (latest pointer was "
                           "%r)", os.path.join(load_dir, tag), pointed)
        return tag

    # one fixed-size slot per rank: the agreement collective must have a
    # static shape, so tags are padded/truncated to this many bytes
    _TAG_AGREE_BYTES = 256

    def _agree_resume_tag(self, tag: Optional[str]) -> Optional[str]:
        """Barrier-agreed resume tag: every rank allgathers its locally
        resolved candidate and adopts rank 0's. Resolution reads a shared
        directory, so ranks *usually* agree — but a save/quarantine racing
        a restart can split the view, and a pod whose ranks resume
        different steps silently diverges forever. The allgather doubles
        as the resume barrier: no rank starts loading until every rank has
        resolved. Single-process: identity."""
        if jax.process_count() == 1:
            return tag
        from jax.experimental import multihost_utils  # pragma: no cover

        buf = np.zeros(self._TAG_AGREE_BYTES, np.uint8)
        enc = (tag or "").encode()[:self._TAG_AGREE_BYTES]
        buf[:len(enc)] = np.frombuffer(enc, np.uint8)
        rows = np.asarray(multihost_utils.process_allgather(buf))
        agreed = bytes(rows.reshape(jax.process_count(), -1)[0]) \
            .rstrip(b"\x00").decode() or None
        if agreed != tag:
            from ..monitor.monitor import resilience_counters

            logger.warning(
                "resume-tag divergence: this rank resolved %r but the pod "
                "agreed on rank 0's %r — adopting the pod's choice", tag,
                agreed)
            resilience_counters.incr("fallback_loads")
        return agreed

    def save_16bit_model(self, save_dir: str,
                         checkpoint_name: str = "mp_rank_00_model_states.pt"
                         ) -> str:
        """Gather full (unsharded) weights and write one bf16 state-dict file
        (reference ``zero_gather_16bit_weights_on_model_save`` → engine
        ``save_16bit_model``, ``engine.py:771``). The gather the reference does
        with ZeRO-3 collectives is a host ``device_get`` of the logical array
        here — XLA assembles shards transparently."""
        import torch

        os.makedirs(save_dir, exist_ok=True)
        out = os.path.join(save_dir, checkpoint_name)
        from ..checkpoint.engine import _leaf_paths

        names = _leaf_paths(self.params)
        leaves = jax.tree_util.tree_leaves(self.params)
        sd = {}
        for name, leaf in zip(names, leaves):
            arr = np.asarray(jax.device_get(leaf))
            # jnp.issubdtype: ml_dtypes bfloat16 is not np.floating
            if jnp.issubdtype(arr.dtype, jnp.floating):
                # torch has no bfloat16 numpy bridge: go through fp32 view
                sd[name] = torch.from_numpy(
                    np.ascontiguousarray(arr.astype(np.float32))).bfloat16()
            else:
                sd[name] = torch.from_numpy(np.ascontiguousarray(arr))
        torch.save(sd, out)
        log_dist(f"saved 16-bit model to {out}")
        return out

    def _validate_tag(self, tag: str):
        """Tag agreement across processes (reference ``_checkpoint_tag_validation:
        3033`` — bf16 allreduce of the tag hash)."""
        mode = self.config.checkpoint.tag_validation
        if mode == "Ignore" or jax.process_count() == 1:
            return
        # multi-controller: compare a tag digest via a tiny device allreduce.
        # Must be deterministic across processes — Python's str hash is salted
        # per-process (PYTHONHASHSEED), so crc32 instead.
        import zlib

        h = float(zlib.crc32(tag.encode()) % (2 ** 16))
        arr = jnp.full((jax.local_device_count(),), h)
        total = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(arr)
        expect = h * jax.device_count()
        if not np.allclose(np.asarray(total)[0], expect):
            msg = f"checkpoint tag {tag!r} differs across ranks"
            if mode == "Fail":
                raise RuntimeError(msg)
            logger.warning(msg)

    # ================================================================ misc
    def eval_batch(self, batch):
        """Loss on a batch WITHOUT touching training state (does not cache the
        batch for backward(), unlike :meth:`forward`)."""
        if self._eval_fn is None:
            self._eval_fn = jax.jit(
                lambda p, b, r: self._loss_and_metrics(p, b, r,
                                                       train=False)[0])
        return self._eval_fn(self.params, batch,
                             jax.random.fold_in(self._rng, self.micro_steps))

    def __call__(self, batch):
        return self.forward(batch)
