"""NVMe tensor swapping (ZeRO-Infinity style offload).

Analog of ``deepspeed/runtime/swap_tensor/`` (1811 LoC: ``AsyncTensorSwapper``,
``OptimizerSwapper``, ``partitioned_param_swapper``) on the C++ aio op
(``ops/aio.py`` ↔ reference ``csrc/aio``). Tensors round-trip host↔disk fully
asynchronously; ``prefetch`` starts reads early so ``retrieve`` overlaps disk
latency with compute — the same swap-in-ahead pattern ZeRO-3's NVMe path uses
(``partitioned_param_coordinator.__prefetch_nvme_param_partitions``,
``stage3.py`` optimizer-state swap-in at ``:1816``).

Device arrays are pulled to host numpy at swap-out; swap-in returns numpy and
the caller re-places onto the mesh (``jax.device_put`` against its sharding) —
placement stays the engine's concern, matching the layering upstream.
"""
import os
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..utils.logging import logger


@dataclass
class _SwapEntry:
    path: str
    shape: tuple
    dtype: Any
    write_req: Optional[int] = None   # in-flight write
    read_req: Optional[int] = None    # in-flight prefetch
    read_buf: Optional[np.ndarray] = None


class AsyncTensorSwapper:
    """Named-tensor swap pool over one aio handle."""

    def __init__(self, swap_dir: str, n_threads: int = 4):
        from ..ops.aio import AsyncIOHandle

        os.makedirs(swap_dir, exist_ok=True)
        self.swap_dir = swap_dir
        self.handle = AsyncIOHandle(n_threads)
        self._entries: Dict[str, _SwapEntry] = {}

    # ------------------------------------------------------------------ out
    def swap_out(self, name: str, tensor) -> None:
        """Start an async write; returns immediately. The host copy stays
        referenced by the aio handle until the write completes."""
        import hashlib

        arr = np.asarray(jax.device_get(tensor))
        # readable prefix + name hash: replace() alone is not injective
        # ('a/b' vs 'a__b' must not alias to one file)
        digest = hashlib.sha1(name.encode()).hexdigest()[:10]
        path = os.path.join(
            self.swap_dir, f"{name.replace('/', '__')}.{digest}.swp")
        e = self._entries.get(name)
        if e is not None:
            # reap ALL in-flight IO on this name: rewriting while an old
            # read/write runs would race on the file and leak the request
            for req in (e.write_req, e.read_req):
                if req is not None:
                    try:
                        self.handle.wait(req)
                    except OSError:
                        pass
        e = _SwapEntry(path=path, shape=arr.shape, dtype=arr.dtype)
        # whole-file rewrite: a shrinking tensor must not leave stale tail bytes
        e.write_req = self.handle.pwrite(path, arr, truncate=True)
        self._entries[name] = e

    # ------------------------------------------------------------------- in
    def prefetch(self, name: str) -> None:
        """Begin the disk read now; ``retrieve`` later only waits the tail."""
        e = self._require(name)
        if e.read_req is not None:
            return  # already in flight
        if e.write_req is not None:
            req, e.write_req = e.write_req, None  # clear first: wait() reaps
            self.handle.wait(req)                 # even on failure
        e.read_buf = np.empty(e.shape, e.dtype)
        e.read_req = self.handle.pread(e.path, e.read_buf)

    def retrieve(self, name: str) -> np.ndarray:
        e = self._require(name)
        if e.read_req is None:
            self.prefetch(name)
        req, buf = e.read_req, e.read_buf
        e.read_req, e.read_buf = None, None  # wait() reaps even on failure;
        self.handle.wait(req)                # a retry must re-issue the read
        return buf

    # ----------------------------------------------------------------- misc
    def synchronize(self) -> None:
        """Drain all in-flight writes (checkpoint barrier)."""
        for e in self._entries.values():
            if e.write_req is not None:
                req, e.write_req = e.write_req, None  # reaped even on failure
                self.handle.wait(req)

    def release(self, name: str) -> None:
        e = self._entries.pop(name, None)
        if e is None:
            return
        for req in (e.write_req, e.read_req):
            if req is not None:
                try:
                    self.handle.wait(req)
                except OSError:
                    pass
        try:
            os.unlink(e.path)
        except OSError:
            pass

    def swapped_names(self):
        return list(self._entries)

    def _require(self, name: str) -> _SwapEntry:
        if name not in self._entries:
            raise KeyError(f"tensor {name!r} was never swapped out")
        return self._entries[name]

    def close(self):
        try:
            self.synchronize()
        except Exception:
            logger.warning("swapper close: pending IO abandoned")
        self.handle.close()
