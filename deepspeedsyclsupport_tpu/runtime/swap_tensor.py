"""NVMe tensor swapping (ZeRO-Infinity style offload).

Analog of ``deepspeed/runtime/swap_tensor/`` (1811 LoC: ``AsyncTensorSwapper``,
``OptimizerSwapper``, ``partitioned_param_swapper``) on the C++ aio op
(``ops/aio.py`` ↔ reference ``csrc/aio``). Tensors round-trip host↔disk fully
asynchronously; ``prefetch`` starts reads early so ``retrieve`` overlaps disk
latency with compute — the same swap-in-ahead pattern ZeRO-3's NVMe path uses
(``partitioned_param_coordinator.__prefetch_nvme_param_partitions``,
``stage3.py`` optimizer-state swap-in at ``:1816``).

Device arrays are pulled to host numpy at swap-out; swap-in returns numpy and
the caller re-places onto the mesh (``jax.device_put`` against its sharding) —
placement stays the engine's concern, matching the layering upstream.

Fault path: every IO completion point rides
:func:`~..utils.fault_injection.retry_io` (capped exponential backoff +
jitter, ``Resilience/io_retries`` counted), and a failed request is
*re-issued*, not just re-awaited — the host copy of an un-durable write is
retained until its completion is confirmed, so a transient NVMe/FS blip
degrades an offloaded step to a retry instead of killing the run. The
retained copy costs nothing extra: the aio handle already pins the buffer
until the request is reaped.
"""
import os
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..utils.fault_injection import get_fault_injector, retry_io
from ..utils.logging import logger


@dataclass
class _SwapEntry:
    path: str
    shape: tuple
    dtype: Any
    write_req: Optional[int] = None   # in-flight write
    write_buf: Optional[np.ndarray] = None  # host copy until write durable
    read_req: Optional[int] = None    # in-flight prefetch
    read_buf: Optional[np.ndarray] = None


class AsyncTensorSwapper:
    """Named-tensor swap pool over one aio handle."""

    def __init__(self, swap_dir: str, n_threads: int = 4):
        from ..ops.aio import AsyncIOHandle

        os.makedirs(swap_dir, exist_ok=True)
        self.swap_dir = swap_dir
        self.handle = AsyncIOHandle(n_threads)
        self._entries: Dict[str, _SwapEntry] = {}

    # ------------------------------------------------------------------ out
    def swap_out(self, name: str, tensor) -> None:
        """Start an async write; returns immediately. The host copy stays
        referenced (entry + aio handle) until the write is confirmed
        durable, so a failed write can be re-issued by the retry path."""
        import hashlib

        arr = np.asarray(jax.device_get(tensor))
        # readable prefix + name hash: replace() alone is not injective
        # ('a/b' vs 'a__b' must not alias to one file)
        digest = hashlib.sha1(name.encode()).hexdigest()[:10]
        path = os.path.join(
            self.swap_dir, f"{name.replace('/', '__')}.{digest}.swp")
        e = self._entries.get(name)
        if e is not None:
            # reap ALL in-flight IO on this name: rewriting while an old
            # read/write runs would race on the file and leak the request
            for req in (e.write_req, e.read_req):
                if req is not None:
                    try:
                        self.handle.wait(req)
                    except OSError:
                        pass
        e = _SwapEntry(path=path, shape=arr.shape, dtype=arr.dtype,
                       write_buf=arr)

        def submit():
            get_fault_injector().maybe_fail_write(path)
            # whole-file rewrite: a shrinking tensor must not leave stale
            # tail bytes
            return self.handle.pwrite(path, arr, truncate=True)

        e.write_req = retry_io(submit, what=f"swap write submit {name}")
        self._entries[name] = e

    def _reap_write(self, name: str, e: _SwapEntry) -> None:
        """Wait out the pending write; a failure re-submits from the
        retained host copy (retry_io pacing + counters) — the entry's data
        only becomes re-readable from disk once this returns."""
        if e.write_req is None:
            return

        def unit():
            if e.write_req is None:
                # prior wait failed and reaped the request: re-issue the
                # whole write from the retained host copy
                get_fault_injector().maybe_fail_write(e.path)
                e.write_req = self.handle.pwrite(e.path, e.write_buf,
                                                 truncate=True)
            req, e.write_req = e.write_req, None  # wait() reaps even on fail
            self.handle.wait(req)

        retry_io(unit, what=f"swap write {name}")
        e.write_buf = None  # durable: release the host copy

    # ------------------------------------------------------------------- in
    def prefetch(self, name: str) -> None:
        """Begin the disk read now; ``retrieve`` later only waits the tail."""
        e = self._require(name)
        if e.read_req is not None:
            return  # already in flight
        self._reap_write(name, e)
        e.read_buf = np.empty(e.shape, e.dtype)
        # submission retried like swap_out's: a transient submit failure
        # must degrade to a retry, not kill the prefetching step
        e.read_req = retry_io(lambda: self.handle.pread(e.path, e.read_buf),
                              what=f"swap read submit {name}")

    def retrieve(self, name: str) -> np.ndarray:
        e = self._require(name)

        def unit():
            if e.read_req is None:
                self.prefetch(name)  # re-issues the read after a failure
            req, buf = e.read_req, e.read_buf
            e.read_req, e.read_buf = None, None  # wait() reaps even on fail
            self.handle.wait(req)
            return buf

        return retry_io(unit, what=f"swap read {name}")

    # ----------------------------------------------------------------- misc
    def synchronize(self) -> None:
        """Drain all in-flight writes (checkpoint barrier) — each one
        retried/re-issued on transient failure like any reap."""
        for name, e in self._entries.items():
            self._reap_write(name, e)

    def release(self, name: str) -> None:
        e = self._entries.pop(name, None)
        if e is None:
            return
        for req in (e.write_req, e.read_req):
            if req is not None:
                try:
                    self.handle.wait(req)
                except OSError:
                    pass
        e.write_buf = e.read_buf = None
        try:
            os.unlink(e.path)
        except OSError:
            pass

    def swapped_names(self):
        return list(self._entries)

    def _require(self, name: str) -> _SwapEntry:
        if name not in self._entries:
            raise KeyError(f"tensor {name!r} was never swapped out")
        return self._entries[name]

    def close(self):
        try:
            self.synchronize()
        except Exception:
            logger.warning("swapper close: pending IO abandoned")
        self.handle.close()
