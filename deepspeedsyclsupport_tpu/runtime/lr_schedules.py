"""LR schedules.

Analog of ``deepspeed/runtime/lr_schedules.py`` (878 LoC: LRRangeTest, OneCycle,
WarmupLR, WarmupDecayLR, WarmupCosineLR). The reference implements stateful
per-step ``.step()`` objects mutating optimizer param groups; the TPU design expresses
each as a pure ``step -> lr`` schedule (optax convention) compiled into the jitted
update, so LR math costs nothing at runtime and is checkpoint-free (the step counter
lives in the optimizer state).
"""
import math
from typing import Any, Callable, Dict, Optional

import optax

Schedule = Callable[[Any], Any]

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"

VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR,
                      WARMUP_COSINE_LR]


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000, warmup_type: str = "log") -> Schedule:
    """WarmupLR (reference ``lr_schedules.py`` class WarmupLR): ramp from min to max
    over ``warmup_num_steps`` (log or linear), then hold."""
    import jax.numpy as jnp

    warmup_num_steps = max(2, warmup_num_steps)

    def sched(step):
        s = jnp.minimum(jnp.asarray(step, jnp.float32), warmup_num_steps)
        if warmup_type == "log":
            frac = jnp.log1p(s) / math.log(warmup_num_steps + 1)
        else:
            frac = s / warmup_num_steps
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * jnp.minimum(frac, 1.0)

    return sched


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                    warmup_type: str = "log") -> Schedule:
    """WarmupDecayLR: warmup then linear decay to 0 at ``total_num_steps``."""
    import jax.numpy as jnp

    base = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def sched(step):
        s = jnp.asarray(step, jnp.float32)
        decay = jnp.clip((total_num_steps - s) /
                         max(1.0, total_num_steps - warmup_num_steps), 0.0, 1.0)
        return jnp.where(s < warmup_num_steps, base(step), warmup_max_lr * decay)

    return sched


def warmup_cosine_lr(total_num_steps: int, warmup_min_ratio: float = 0.0,
                     warmup_num_steps: int = 1000, cos_min_ratio: float = 0.0001,
                     warmup_max_lr: float = 0.001) -> Schedule:
    """WarmupCosineLR: linear warmup then cosine decay to ``cos_min_ratio``."""
    import jax.numpy as jnp

    def sched(step):
        s = jnp.asarray(step, jnp.float32)
        warm = warmup_min_ratio + (1 - warmup_min_ratio) * jnp.minimum(
            s / max(1, warmup_num_steps), 1.0)
        prog = jnp.clip((s - warmup_num_steps) /
                        max(1, total_num_steps - warmup_num_steps), 0.0, 1.0)
        cos = cos_min_ratio + (1 - cos_min_ratio) * 0.5 * (1 + jnp.cos(math.pi * prog))
        ratio = jnp.where(s < warmup_num_steps, warm, cos)
        return warmup_max_lr * ratio

    return sched


def one_cycle(cycle_min_lr: float, cycle_max_lr: float,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: Optional[int] = None,
              decay_step_size: int = 0, decay_lr_rate: float = 0.0,
              **_ignored) -> Schedule:
    """OneCycle (reference ``lr_schedules.py`` class OneCycle): min→max over the
    first leg, max→min over the second, then optional decay below min."""
    import jax.numpy as jnp

    second = cycle_second_step_size if cycle_second_step_size is not None \
        else cycle_first_step_size
    cycle_len = cycle_first_step_size + second

    def sched(step):
        s = jnp.asarray(step, jnp.float32)
        up = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * (s / cycle_first_step_size)
        down = cycle_max_lr - (cycle_max_lr - cycle_min_lr) * (
            (s - cycle_first_step_size) / max(1, second))
        in_cycle = jnp.where(s < cycle_first_step_size, up, jnp.maximum(down, cycle_min_lr))
        if decay_step_size > 0:
            decayed = cycle_min_lr * jnp.maximum(
                1.0 - decay_lr_rate * ((s - cycle_len) / decay_step_size), 0.0)
            return jnp.where(s < cycle_len, in_cycle, decayed)
        return in_cycle

    return sched


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False) -> Schedule:
    """LRRangeTest (reference ``lr_schedules.py`` class LRRangeTest): linearly
    increasing LR sweep for finding LR bounds."""
    import jax.numpy as jnp

    def sched(step):
        s = jnp.asarray(step, jnp.float32)
        interval = jnp.floor(s / lr_range_test_step_size) if lr_range_test_staircase \
            else s / lr_range_test_step_size
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return sched


_FACTORIES: Dict[str, Callable[..., Schedule]] = {
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
    WARMUP_COSINE_LR: warmup_cosine_lr,
    ONE_CYCLE: one_cycle,
    LR_RANGE_TEST: lr_range_test,
}


def build_schedule(sched_type: Optional[str], params: Dict[str, Any],
                   base_lr: float) -> Schedule:
    """Config → schedule (reference: engine ``_configure_lr_scheduler``)."""
    if sched_type is None:
        return optax.constant_schedule(base_lr)
    if sched_type not in _FACTORIES:
        raise ValueError(f"scheduler type {sched_type!r} not in {VALID_LR_SCHEDULES}")
    return _FACTORIES[sched_type](**params)
