"""Progressive Layer Dropping (PLD) — compressed-training layer-drop schedule.

Analog of the reference's ``runtime/progressive_layer_drop.py:10``
(PLD, arXiv:2010.13369): the keep-probability schedule
``θ(t) = (1 − θ̄)·e^(−γ·t) + θ̄`` starts at 1 (keep everything) and decays
toward the configured floor ``θ̄``; depth scales the per-layer keep
probability ``p_l = 1 − (l+1)/L · (1 − θ(t))`` so late layers drop more.

The schedule lives host-side; the engine injects the current θ into each
batch as a traced scalar (``batch["pld_theta"]``) so no retracing happens as
θ decays, and the model's layer scan skips dropped layers with ``lax.cond``
— a dropped layer costs neither FLOPs nor memory that step.
"""
import math
from typing import Any, Dict

from ..utils.logging import log_dist

__all__ = ["ProgressiveLayerDrop"]


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = float(theta)
        self.gamma = float(gamma)
        self.current_theta = 1.0
        log_dist(f"Enabled progressive layer dropping (theta = {self.theta})")

    def get_state(self) -> Dict[str, Any]:
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        self.current_theta = ((1.0 - self.theta)
                              * math.exp(-self.gamma * global_step)
                              + self.theta)
        return self.current_theta
