"""1-bit Adam / 1-bit LAMB optimizer analogs.

Reference: ``runtime/fp16/onebit/{adam,lamb,zoadam}.py`` (1108 LoC) layered on
compressed comm backends (``runtime/comm/nccl.py`` etc.). Algorithm (1-bit
Adam, Tang et al.): run vanilla Adam for ``freeze_step`` warmup steps; then
FREEZE the variance ``v`` and switch the momentum update to 1-bit compressed
communication with error feedback.

TPU-native shape: an optax gradient transformation. In the SPMD engine the
gradient mean is fused into the backward pass by GSPMD, so there is no
separate allreduce to compress — the transform's compression stage instead
applies the same sign+scale+error-feedback operator to the *momentum* locally
(matching the reference's server-side math exactly; unbiased over steps via
the residual). For manual shard_map DP loops, pass ``axis_name`` and the
momentum is additionally averaged over that axis with
:func:`~deepspeedsyclsupport_tpu.comm.quantized.compressed_allreduce` — the
true wire-compressed path.
"""
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from ..comm.quantized import compressed_allreduce


class OneBitAdamState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates
    nu: optax.Updates
    error: optax.Updates  # compression residual (error feedback)


def scale_by_onebit_adam(b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                         freeze_step: int = 100,
                         axis_name: Optional[str] = None
                         ) -> optax.GradientTransformation:
    def init_fn(params):
        zeros = lambda: jax.tree_util.tree_map(  # noqa: E731
            lambda p: jnp.zeros_like(p, jnp.float32), params)
        return OneBitAdamState(jnp.zeros((), jnp.int32), zeros(), zeros(),
                               zeros())

    def update_fn(updates, state, params=None):
        from ..comm.quantized import sign_compress

        count = state.count + 1
        in_warmup = count <= freeze_step
        # During warmup ranks must stay in lockstep (reference runs DENSE
        # all-reduced Adam pre-freeze): average gradients over the DP axis
        # before they touch momentum/variance.
        if axis_name is not None:
            g_sync = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, axis_name), updates)
        else:
            g_sync = updates
        # momentum: synced grads in warmup, LOCAL grads after (the per-step
        # sync then happens through the compressed collective, as upstream)
        mu = jax.tree_util.tree_map(
            lambda m, gs, gl: b1 * m + (1 - b1) * jnp.where(
                in_warmup, gs, gl).astype(jnp.float32),
            state.mu, g_sync, updates)
        # variance: tracked (from synced grads) during warmup, FROZEN after
        nu = jax.tree_util.tree_map(
            lambda v, g: jnp.where(
                in_warmup, b2 * v + (1 - b2) * jnp.square(
                    g.astype(jnp.float32)), v),
            state.nu, g_sync)

        def compress(m, e):
            if axis_name is not None:
                return compressed_allreduce(m, e, axis_name)
            sign, scale, residual = sign_compress(m + e)
            return scale * sign.astype(jnp.float32), residual

        flat_mu, treedef = jax.tree_util.tree_flatten(mu)
        flat_err = jax.tree_util.tree_leaves(state.error)
        pairs = [compress(m, e) for m, e in zip(flat_mu, flat_err)]
        mu_comp = jax.tree_util.tree_unflatten(treedef, [p[0] for p in pairs])
        new_err = jax.tree_util.tree_unflatten(treedef, [p[1] for p in pairs])
        # warmup: exact momentum, zero residual
        mu_eff = jax.tree_util.tree_map(
            lambda exact, comp: jnp.where(in_warmup, exact, comp), mu, mu_comp)
        error = jax.tree_util.tree_map(
            lambda e, ne: jnp.where(in_warmup, jnp.zeros_like(e), ne),
            state.error, new_err)

        bc1 = 1 - b1 ** count.astype(jnp.float32)
        bc2 = 1 - b2 ** jnp.minimum(count, freeze_step).astype(jnp.float32)
        out = jax.tree_util.tree_map(
            lambda m, v, g: ((m / bc1) / (jnp.sqrt(v / bc2) + eps)).astype(
                g.dtype),
            mu_eff, nu, updates)
        # CRITICAL (1-bit Adam Alg. 1): the momentum RECURSION carries the
        # compressed-averaged value, not the raw local one — the residual
        # lives in `error`, and carrying raw mu double-counts it step after
        # step (observed: divergence on long runs).
        return out, OneBitAdamState(count, mu_eff, nu, error)

    return optax.GradientTransformation(init_fn, update_fn)


def onebit_adam(learning_rate, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-8, freeze_step: int = 100,
                weight_decay: float = 0.0,
                axis_name: Optional[str] = None
                ) -> optax.GradientTransformation:
    """Drop-in 1-bit Adam (reference ``OnebitAdam``,
    ``runtime/fp16/onebit/adam.py``)."""
    txs = [scale_by_onebit_adam(b1, b2, eps, freeze_step, axis_name)]
    if weight_decay:
        txs.append(optax.add_decayed_weights(weight_decay))
    txs.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*txs)
