"""1-bit Adam / 1-bit LAMB optimizer analogs.

Reference: ``runtime/fp16/onebit/{adam,lamb,zoadam}.py`` (1108 LoC) layered on
compressed comm backends (``runtime/comm/nccl.py`` etc.). Algorithm (1-bit
Adam, Tang et al.): run vanilla Adam for ``freeze_step`` warmup steps; then
FREEZE the variance ``v`` and switch the momentum update to 1-bit compressed
communication with error feedback.

TPU-native shape: an optax gradient transformation. In the SPMD engine the
gradient mean is fused into the backward pass by GSPMD, so there is no
separate allreduce to compress — the transform's compression stage instead
applies the same sign+scale+error-feedback operator to the *momentum* locally
(matching the reference's server-side math exactly; unbiased over steps via
the residual). For manual shard_map DP loops, pass ``axis_name`` and the
momentum is additionally averaged over that axis with
:func:`~deepspeedsyclsupport_tpu.comm.quantized.compressed_allreduce` — the
true wire-compressed path.
"""
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..comm.quantized import compressed_allreduce


class OneBitAdamState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates
    nu: optax.Updates
    error: optax.Updates  # compression residual (error feedback)


def scale_by_onebit_adam(b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                         freeze_step: int = 100,
                         axis_name: Optional[str] = None
                         ) -> optax.GradientTransformation:
    def init_fn(params):
        zeros = lambda: jax.tree_util.tree_map(  # noqa: E731
            lambda p: jnp.zeros_like(p, jnp.float32), params)
        return OneBitAdamState(jnp.zeros((), jnp.int32), zeros(), zeros(),
                               zeros())

    def update_fn(updates, state, params=None):
        from ..comm.quantized import sign_compress

        count = state.count + 1
        in_warmup = count <= freeze_step
        # During warmup ranks must stay in lockstep (reference runs DENSE
        # all-reduced Adam pre-freeze): average gradients over the DP axis
        # before they touch momentum/variance.
        if axis_name is not None:
            g_sync = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, axis_name), updates)
        else:
            g_sync = updates
        # momentum: synced grads in warmup, LOCAL grads after (the per-step
        # sync then happens through the compressed collective, as upstream)
        mu = jax.tree_util.tree_map(
            lambda m, gs, gl: b1 * m + (1 - b1) * jnp.where(
                in_warmup, gs, gl).astype(jnp.float32),
            state.mu, g_sync, updates)
        # variance: tracked (from synced grads) during warmup, FROZEN after
        nu = jax.tree_util.tree_map(
            lambda v, g: jnp.where(
                in_warmup, b2 * v + (1 - b2) * jnp.square(
                    g.astype(jnp.float32)), v),
            state.nu, g_sync)

        def compress(m, e):
            if axis_name is not None:
                return compressed_allreduce(m, e, axis_name)
            sign, scale, residual = sign_compress(m + e)
            return scale * sign.astype(jnp.float32), residual

        flat_mu, treedef = jax.tree_util.tree_flatten(mu)
        flat_err = jax.tree_util.tree_leaves(state.error)
        pairs = [compress(m, e) for m, e in zip(flat_mu, flat_err)]
        mu_comp = jax.tree_util.tree_unflatten(treedef, [p[0] for p in pairs])
        new_err = jax.tree_util.tree_unflatten(treedef, [p[1] for p in pairs])
        # warmup: exact momentum, zero residual
        mu_eff = jax.tree_util.tree_map(
            lambda exact, comp: jnp.where(in_warmup, exact, comp), mu, mu_comp)
        error = jax.tree_util.tree_map(
            lambda e, ne: jnp.where(in_warmup, jnp.zeros_like(e), ne),
            state.error, new_err)

        bc1 = 1 - b1 ** count.astype(jnp.float32)
        bc2 = 1 - b2 ** jnp.minimum(count, freeze_step).astype(jnp.float32)
        out = jax.tree_util.tree_map(
            lambda m, v, g: ((m / bc1) / (jnp.sqrt(v / bc2) + eps)).astype(
                g.dtype),
            mu_eff, nu, updates)
        # CRITICAL (1-bit Adam Alg. 1): the momentum RECURSION carries the
        # compressed-averaged value, not the raw local one — the residual
        # lives in `error`, and carrying raw mu double-counts it step after
        # step (observed: divergence on long runs).
        return out, OneBitAdamState(count, mu_eff, nu, error)

    return optax.GradientTransformation(init_fn, update_fn)


def onebit_adam(learning_rate, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-8, freeze_step: int = 100,
                weight_decay: float = 0.0,
                axis_name: Optional[str] = None
                ) -> optax.GradientTransformation:
    """Drop-in 1-bit Adam (reference ``OnebitAdam``,
    ``runtime/fp16/onebit/adam.py``)."""
    txs = [scale_by_onebit_adam(b1, b2, eps, freeze_step, axis_name)]
    if weight_decay:
        txs.append(optax.add_decayed_weights(weight_decay))
    txs.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*txs)


def _compress(x, e, axis_name):
    """Shared 1-bit dispatch: sign+scale+error-feedback locally, or the
    wire-compressed allreduce over ``axis_name`` inside shard_map. One
    implementation — the sign/scale convention must agree everywhere or
    error feedback breaks (see ``sign_compress``)."""
    from ..comm.quantized import sign_compress

    if axis_name is not None:
        return compressed_allreduce(x, e, axis_name)
    sign, scale, residual = sign_compress(x + e)
    return scale * sign.astype(jnp.float32), residual


def _map_unzip(fn, n_out, *trees):
    """tree_map for multi-output leaf fns, robust to tuple-valued pytrees
    (the naive ``is_leaf=isinstance(tuple)`` trick misparses params that are
    themselves tuples). Returns ``n_out`` trees shaped like ``trees[0]``."""
    treedef = jax.tree_util.tree_structure(trees[0])
    leaves = [jax.tree_util.tree_leaves(t) for t in trees]
    assert all(len(l) == len(leaves[0]) for l in leaves)
    outs = [fn(*args) for args in zip(*leaves)]
    return tuple(jax.tree_util.tree_unflatten(treedef, [o[i] for o in outs])
                 for i in range(n_out))


class OneBitLambState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates
    nu: optax.Updates            # frozen after freeze_step
    nu_fresh: optax.Updates      # keeps tracking via reconstructed grads
    error: optax.Updates         # compression residual
    scaling_coeff: optax.Updates   # per-leaf scalar, set at the freeze step
    lamb_coeff_freeze: optax.Updates  # per-leaf EMA of the warmup lamb coeff
    last_factor: optax.Updates       # per-leaf factor rate limiter


def onebit_lamb(learning_rate, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-8, freeze_step: int = 100,
                weight_decay: float = 0.0,
                max_coeff: float = 10.0, min_coeff: float = 0.01,
                coeff_beta: float = 0.9, factor_max: float = 4.0,
                factor_min: float = 0.5, factor_threshold: float = 0.1,
                axis_name: Optional[str] = None
                ) -> optax.GradientTransformation:
    """1-bit LAMB (reference ``OnebitLamb``, ``runtime/fp16/onebit/lamb.py``).

    Warmup runs baseline LAMB (dense-synced grads when ``axis_name`` is
    given) while an EMA of the clipped trust ratio is collected per leaf
    (``coeff_beta``, reference ``lamb.py:238-240``). At the freeze step the
    variance freezes and per-leaf ``scaling_coeff`` = united-scale /
    leaf-momentum-scale balances compression error across leaves
    (``lamb.py:171-181``). Afterwards momentum updates use LOCAL gradients
    and synchronize ONLY through the 1-bit compressed operator (the whole
    point of the algorithm — the reference does the same switch); a fresh
    variance tracks reconstructed gradients and the trust ratio becomes
    ``lamb_coeff_freeze × factor`` with ``factor = clip(max(frozen_denom /
    fresh_denom))`` rate-limited by ``factor_threshold``
    (``lamb.py:333-360``).

    Consumes the learning rate internally (the trust ratio composes with
    it); do NOT chain a separate ``scale_by_learning_rate``.
    """

    def lr_at(count):
        return learning_rate(count) if callable(learning_rate) else learning_rate

    def init_fn(params):
        zeros = lambda: jax.tree_util.tree_map(  # noqa: E731
            lambda p: jnp.zeros_like(p, jnp.float32), params)
        scalars = lambda v: jax.tree_util.tree_map(  # noqa: E731
            lambda _: jnp.asarray(v, jnp.float32), params)
        return OneBitLambState(jnp.zeros((), jnp.int32), zeros(), zeros(),
                               zeros(), zeros(), scalars(1.0), scalars(0.0),
                               scalars(1.0))

    def update_fn(updates, state, params):
        if params is None:
            raise ValueError("onebit_lamb needs params (trust ratio)")
        count = state.count + 1
        in_warmup = count <= freeze_step
        at_freeze = count == freeze_step
        lr = lr_at(state.count)

        # dense sync only in warmup; post-freeze the compressed momentum
        # collective is the ONLY cross-rank communication
        if axis_name is not None:
            g_dense = jax.tree_util.tree_map(
                lambda u: jax.lax.pmean(u, axis_name), updates)
        else:
            g_dense = updates
        g_local = updates

        # ---------------- warmup: baseline LAMB + coeff EMA ----------------
        mu_w = jax.tree_util.tree_map(
            lambda m, gg: b1 * m + (1 - b1) * gg.astype(jnp.float32),
            state.mu, g_dense)
        nu_w = jax.tree_util.tree_map(
            lambda v, gg: b2 * v + (1 - b2) * jnp.square(
                gg.astype(jnp.float32)), state.nu, g_dense)

        def warm_leaf(m, v, p, coeff_ema):
            upd = m / (jnp.sqrt(v) + eps)
            if weight_decay > 0.0:
                upd = upd + weight_decay * p.astype(jnp.float32)
            w_norm = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
            u_norm = jnp.sqrt(jnp.sum(jnp.square(upd)))
            coeff = jnp.clip(w_norm / jnp.maximum(u_norm, 1e-12),
                             min_coeff, max_coeff)
            coeff = jnp.where((w_norm > 0) & (u_norm > 0), coeff, 1.0)
            new_ema = jnp.where(
                coeff != 1.0,
                coeff_beta * coeff_ema + (1 - coeff_beta) * coeff, coeff_ema)
            return -lr * coeff * upd, new_ema

        warm_delta, warm_ema = _map_unzip(warm_leaf, 2, mu_w, nu_w, params,
                                          state.lamb_coeff_freeze)

        # scaling coeff at the freeze transition (lamb.py:171-181) — a full
        # tree reduction, gated behind lax.cond so it costs nothing on the
        # other steps
        def compute_scaling(_):
            mu_leaves = jax.tree_util.tree_leaves(mu_w)
            scales = [jnp.sqrt(jnp.sum(jnp.square(m))) / np.sqrt(m.size)
                      for m in mu_leaves]
            united = sum(scales) / len(scales)
            treedef = jax.tree_util.tree_structure(state.mu)
            return jax.tree_util.tree_unflatten(
                treedef, [united / jnp.maximum(s, 1e-12) for s in scales])

        scaling = jax.lax.cond(at_freeze, compute_scaling,
                               lambda _: state.scaling_coeff, None)

        # ---------------- compression stage --------------------------------
        def comp_leaf(m_prev, gg, e, sc, v_frozen, v_fresh, p, coeff_ema,
                      last_f):
            m_local = (b1 * m_prev + (1 - b1) * gg.astype(jnp.float32)) * sc
            m_synced, new_e = _compress(m_local, e, axis_name)
            m_eff = m_synced / sc
            grad_recon = (m_eff - b1 * m_prev) / (1 - b1)
            v_fresh_new = b2 * v_fresh + (1 - b2) * jnp.square(grad_recon)
            denom = jnp.sqrt(v_frozen) + eps
            denom_real = jnp.sqrt(v_fresh_new) + eps
            prelim = m_eff / denom
            upd = prelim + (weight_decay * p.astype(jnp.float32)
                            if weight_decay > 0.0 else 0.0)
            factor = jnp.clip(jnp.max(denom / denom_real), factor_min,
                              factor_max)
            if weight_decay > 0.0:
                ratio = jnp.minimum(
                    1.0, jnp.sqrt(jnp.sum(jnp.square(prelim))) /
                    jnp.maximum(jnp.sqrt(jnp.sum(jnp.square(upd))), 1e-12))
                factor = factor * ratio + (1.0 - ratio)
            factor = jnp.clip(factor, last_f * (1.0 - factor_threshold),
                              last_f * (1.0 + factor_threshold))
            coeff = coeff_ema * factor
            return -lr * coeff * upd, m_eff, new_e, v_fresh_new, factor

        c_delta, c_mu, c_err, c_fresh, c_factor = _map_unzip(
            comp_leaf, 5, state.mu, g_local, state.error, scaling,
            state.nu, state.nu_fresh, params, state.lamb_coeff_freeze,
            state.last_factor)

        sel = lambda a, b: jax.tree_util.tree_map(  # noqa: E731
            lambda x, y: jnp.where(in_warmup, x, y), a, b)
        delta = sel(warm_delta, c_delta)
        mu = sel(mu_w, c_mu)
        error = sel(jax.tree_util.tree_map(jnp.zeros_like, state.error),
                    c_err)
        nu = jax.tree_util.tree_map(
            lambda v_new, v_old: jnp.where(in_warmup, v_new, v_old),
            nu_w, state.nu)
        # nu_fresh: snapshots nu at the freeze step, then tracks recon grads
        nu_fresh = jax.tree_util.tree_map(
            lambda snap, keep, fresh: jnp.where(
                in_warmup, jnp.where(at_freeze, snap, keep), fresh),
            nu_w, state.nu_fresh, c_fresh)
        last_factor = sel(state.last_factor, c_factor)
        ema = sel(warm_ema, state.lamb_coeff_freeze)
        delta = jax.tree_util.tree_map(
            lambda d, u: d.astype(u.dtype), delta, updates)
        return delta, OneBitLambState(count, mu, nu, nu_fresh, error,
                                      scaling, ema, last_factor)

    return optax.GradientTransformation(init_fn, update_fn)


class ZeroOneAdamState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates
    nu: optax.Updates
    error: optax.Updates
    comm_buffer: optax.Updates   # 'u' accumulator of local deltas
    lrs: jnp.ndarray             # accumulated learning rate since last sync
    var_interval: jnp.ndarray    # int32
    var_counter: jnp.ndarray
    local_interval: jnp.ndarray
    local_counter: jnp.ndarray


def zero_one_adam(learning_rate, b1: float = 0.9, b2: float = 0.999,
                  eps: float = 1e-8,
                  var_freeze_step: int = 100000,
                  var_update_scaler: int = 16,
                  local_step_scaler: int = 32678,
                  local_step_clipper: int = 16,
                  weight_decay: float = 0.0,
                  axis_name: Optional[str] = None
                  ) -> optax.GradientTransformation:
    """0/1 Adam (reference ``ZeroOneAdam``, ``runtime/fp16/onebit/zoadam.py``).

    Variance updates follow the exponential-interval policy (interval
    doubles every ``var_update_scaler`` occurrences) and freeze past
    ``var_freeze_step``. Communication policy with ``axis_name``: variance
    steps sync gradients densely; the in-between pre-freeze steps ship
    1-bit gradients; post-freeze steps are fully LOCAL — parameters advance
    on local momentum while an accumulator collects the deltas, and every
    ``local_interval`` steps (doubling every ``local_step_scaler``, clipped
    at ``local_step_clipper``) the accumulated trajectory is re-synchronized
    through the compressed operator and momentum is rebuilt from it
    (``zoadam.py:243-259``). Without ``axis_name`` (the GSPMD engine) the
    same structure applies the compression operator locally.

    Consumes the learning rate internally (the local-step correction needs
    it); do NOT chain a separate ``scale_by_learning_rate``.
    """

    def lr_at(count):
        return learning_rate(count) if callable(learning_rate) else learning_rate

    def init_fn(params):
        zeros = lambda: jax.tree_util.tree_map(  # noqa: E731
            lambda p: jnp.zeros_like(p, jnp.float32), params)
        one = jnp.ones((), jnp.int32)
        zero = jnp.zeros((), jnp.int32)
        return ZeroOneAdamState(zero, zeros(), zeros(), zeros(), zeros(),
                                jnp.zeros((), jnp.float32), one, zero, one,
                                zero)

    def update_fn(updates, state, params=None):
        if params is None and weight_decay > 0.0:
            raise ValueError("zero_one_adam with weight_decay needs params")
        count = state.count + 1
        lr = lr_at(state.count)
        frozen = count > var_freeze_step
        var_step = jnp.logical_and(~frozen, count % state.var_interval == 0)
        sync_step = jnp.logical_and(frozen,
                                    count % state.local_interval == 0)
        # the error buffer switches metrics at the freeze (gradients →
        # accumulated momentum); the reference re-initializes it
        # (zoadam.py reinitial_error_buffer) — carry-over residuals at the
        # wrong scale destabilize the first syncs
        first_frozen = count == var_freeze_step + 1
        state = state._replace(error=jax.tree_util.tree_map(
            lambda e: jnp.where(first_frozen, jnp.zeros_like(e), e),
            state.error))

        # dense gradient sync ONLY on variance-update steps (zoadam's
        # enable_backward_allreduce toggling); other pre-freeze steps ship
        # 1-bit gradients; post-freeze steps are local
        if axis_name is not None:
            g_dense = jax.tree_util.tree_map(
                lambda u: jax.lax.pmean(u, axis_name), updates)
        else:
            g_dense = updates
        g_local = updates

        def mu_leaf(m, gd, gl, e):
            gf_d = gd.astype(jnp.float32)
            gf_l = gl.astype(jnp.float32)
            g1, e1 = _compress(gf_l, e, axis_name)
            g_eff = jnp.where(var_step, gf_d, jnp.where(frozen, gf_l, g1))
            new_e = jnp.where(var_step | frozen, e, e1)
            return b1 * m + (1 - b1) * g_eff, new_e

        mu, error = _map_unzip(mu_leaf, 2, state.mu, g_dense, g_local,
                               state.error)
        nu = jax.tree_util.tree_map(
            lambda v, gg: jnp.where(
                var_step, b2 * v + (1 - b2) * jnp.square(
                    gg.astype(jnp.float32)), v),
            state.nu, g_dense)

        if params is None:
            local_delta = jax.tree_util.tree_map(
                lambda m, v: -lr * (m / (jnp.sqrt(v) + eps)), mu, nu)
        else:
            local_delta = jax.tree_util.tree_map(
                lambda m, v, p: -lr * (
                    m / (jnp.sqrt(v) + eps)
                    + weight_decay * p.astype(jnp.float32)), mu, nu, params)
        # post-freeze: accumulate local deltas toward the next sync
        buf = jax.tree_util.tree_map(
            lambda b, d: jnp.where(frozen, b + d, b),
            state.comm_buffer, local_delta)
        lrs = jnp.where(frozen, state.lrs + lr, state.lrs)

        # sync step: undo the accumulated local trajectory, re-apply its
        # compressed-synced version, rebuild momentum from it
        def sync_leaf(d, b, v, e, m):
            denom = jnp.sqrt(v) + eps
            b_scaled = b * denom
            b_synced, new_e = _compress(b_scaled, e, axis_name)
            delta_sync = d - b + b_synced / denom
            m_new = -b_synced / jnp.maximum(lrs, 1e-12)
            out_d = jnp.where(sync_step, delta_sync, d)
            out_e = jnp.where(sync_step, new_e, e)
            out_m = jnp.where(sync_step, m_new, m)
            out_b = jnp.where(sync_step, jnp.zeros_like(b), b)
            return out_d, out_e, out_m, out_b

        delta, error, mu, buf = _map_unzip(sync_leaf, 4, local_delta, buf,
                                           nu, error, mu)
        lrs = jnp.where(sync_step, 0.0, lrs)

        # interval bookkeeping (zoadam.py:265-286)
        var_counter = jnp.where(var_step, state.var_counter + 1,
                                state.var_counter)
        bump_var = var_counter == var_update_scaler
        var_interval = jnp.where(bump_var, state.var_interval * 2,
                                 state.var_interval)
        var_counter = jnp.where(bump_var, 0, var_counter)
        local_counter = jnp.where(frozen, state.local_counter + 1,
                                  state.local_counter)
        bump_loc = local_counter == local_step_scaler
        local_interval = jnp.where(
            bump_loc, jnp.minimum(local_step_clipper,
                                  state.local_interval * 2),
            state.local_interval)
        local_counter = jnp.where(bump_loc, 0, local_counter)

        delta = jax.tree_util.tree_map(
            lambda d, u: d.astype(u.dtype), delta, updates)
        return delta, ZeroOneAdamState(count, mu, nu, error, buf, lrs,
                                       var_interval, var_counter,
                                       local_interval, local_counter)

    return optax.GradientTransformation(init_fn, update_fn)
