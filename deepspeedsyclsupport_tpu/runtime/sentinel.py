"""Training-health sentinel: NaN/spike detection and graduated response.

The fault model so far covers process death (resilience.py, rc 217),
collective hangs (comm/watchdog.py, rc 218) and the serving plane (rc 219).
This module closes the remaining class — **numerical faults** — where
nothing crashes: a NaN'd moment tensor or a loss spike silently poisons
every subsequent step and every subsequent checkpoint.

Three-part contract:

* **detect** — cheap health scalars are computed *in-graph* and ride the
  step's existing metrics fetch (``health_*`` keys in ``out_metrics``):
  global nonfinite element count, per-region grad norms named against the
  ``monitor/mfu.py`` ``SCOPE_REGIONS`` registry (a NaN is attributed to
  embed/attn/mlp/head, not just "somewhere"). The host side applies robust
  z-scores (median/MAD over a sliding window, EWMA-smoothed) to loss and
  grad-norm history. Decisions are **lag-deferred** (``cfg.lag`` steps): by
  the time a step's scalars are pulled, that step has retired on device, so
  the ``jax.device_get`` is a read of materialized buffers, not a pipeline
  stall — dslint's ``host-sync-in-step-path`` rule stays clean with exactly
  one sanctioned pull site (``TrainingSentinel._process``).

* **respond** — a graduated ladder. The in-graph gate (a tiny f32 array
  riding the batch under :data:`SENTINEL_GATE_KEY`: ``[loss_cap,
  grad_scale]``) discards any update whose mean loss exceeds the cap
  *before* the host verdict lands, so parameters are never poisoned during
  the lag window (NaN compares false against any cap, so nonfinite losses
  are gated even during warmup). The host ladder then escalates:
  ``warn`` → ``skip_batch`` (journal the stream position; the update was
  already discarded in-graph) → ``rollback`` (reload the newest *last-good*
  tag — one the sentinel promoted only after K healthy steps beyond it, see
  ``checkpoint/engine.py find_last_good_tag`` — rewind the registered
  dataloader, optionally cut LR transiently) → ``abort`` with
  :data:`DIVERGENCE_EXIT_CODE` (220), which the elastic agent classes
  separately from crash/preemption/hang (``--divergence-limit``).

* **prove determinism** — every skip is journaled
  (``health_journal_rank<N>.jsonl``) and the dataloader position rides the
  checkpoint meta, so a rolled-back (or restarted) run re-offers the same
  stream positions and replays the identical skip decisions *pre-dispatch*:
  the replayed trajectory is float-for-float the run that never saw the bad
  batches (tests/unit/test_sentinel.py proves losses hex-identical).

Import hygiene: top level is stdlib + numpy only — the elastic agent
imports :data:`DIVERGENCE_EXIT_CODE` from here and must not drag jax into
the supervisor process. jax is imported lazily inside the in-graph helpers.
"""
import collections
import json
import math
import os
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..monitor.mfu import SCOPE_REGIONS
from ..utils.logging import logger

#: Distinguished "training diverged past the sentinel's ladder" exit code.
#: Sibling of 217 (clean preemption), 218 (collective hang) and 219 (serve
#: hang): outside the shell's signal-death range, classed separately by
#: ``elasticity/elastic_agent.py`` (``divergence_restarts``,
#: ``--divergence-limit``).
DIVERGENCE_EXIT_CODE = 220

#: Batch-dict key the in-graph gate rides under (popped inside
#: ``train_batch_fn`` before the accumulation scan — same rider idiom as
#: ``pld_theta``). Value: f32 ``[loss_cap, grad_scale]``.
SENTINEL_GATE_KEY = "_sentinel_gate"

#: param-path keyword → SCOPE_REGIONS label for the per-region grad-norm
#: breakdown. First match wins; unmatched leaves land in "other" (a DERIVED
#: region in monitor/mfu.py, so the Health/grad_norm.<r> registry entry
#: exists for it).
_REGION_KEYWORDS = (
    ("embed", ("embed", "wte", "wpe", "tok_", "pos_")),
    ("attn", ("attn", "attention", "q_proj", "k_proj", "v_proj", "o_proj",
              "qkv")),
    ("mlp", ("mlp", "ffn", "fc", "dense", "w_in", "w_out", "gate_proj",
             "up_proj", "down_proj")),
    ("head", ("head", "lm_head", "logits", "unembed")),
)

#: regions the grad-norm breakdown can emit (SCOPE minus loss/optimizer,
#: which label *phases*, not parameters) + the unmatched bucket
GRAD_REGIONS = tuple(r for r in SCOPE_REGIONS
                     if r not in ("loss", "optimizer")) + ("other",)


def region_of_param(path: str) -> str:
    """Map a flattened param path (e.g. ``layers/3/attn/q_proj/kernel``) to
    its grad-norm region."""
    low = path.lower()
    for region, keys in _REGION_KEYWORDS:
        if any(k in low for k in keys):
            return region
    return "other"


# ---------------------------------------------------------------- in-graph
def health_metrics(grads) -> Dict[str, Any]:
    """The detect half's device-side scalars, computed on the *unscaled*
    accumulated grads inside the jitted step (``Engine._apply_grads_impl``)
    and returned through ``out_metrics`` — they ride the fetch the step
    already pays for, so arming the sentinel adds no host sync.

    Keys: ``health_nonfinite`` (global nonfinite element count, i32) and
    ``health_rn_<region>`` (per-region grad norm, f32) for every
    :data:`GRAD_REGIONS` member present in the tree."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_flatten_with_path(grads)[0]
    nonfinite = jnp.zeros((), jnp.int32)
    sq: Dict[str, Any] = {}
    for path, g in leaves:
        if not hasattr(g, "dtype") or not jnp.issubdtype(g.dtype,
                                                         jnp.floating):
            continue
        nonfinite = nonfinite + jnp.sum(~jnp.isfinite(g)).astype(jnp.int32)
        region = region_of_param(jax.tree_util.keystr(path))
        sq[region] = sq.get(region, 0.0) + jnp.sum(
            jnp.square(g.astype(jnp.float32)))
    out: Dict[str, Any] = {"health_nonfinite": nonfinite}
    for region, s in sq.items():
        out[f"health_rn_{region}"] = jnp.sqrt(s)
    return out


# ------------------------------------------------------------- host stats
class RobustStat:
    """Sliding-window robust statistics for one scalar series: z-scores are
    (x - median) / (1.4826·MAD), with an EWMA kept alongside for the
    smoothed trend the journal reports. Anomalous samples are *not* fed
    back (the caller only calls :meth:`update` on healthy verdicts), so a
    spike can't widen its own acceptance band."""

    def __init__(self, window: int, alpha: float):
        self.values: collections.deque = collections.deque(maxlen=window)
        self.alpha = alpha
        self.ewma: Optional[float] = None
        # (median, spread) memo — the step path asks for both several times
        # per verdict (z on two series + the gate refresh) and the window
        # only changes on update(); recomputing medians each call was the
        # dominant host-side cost of arming the sentinel
        self._memo: Optional[Tuple[float, float]] = None

    def update(self, x: float) -> None:
        if not math.isfinite(x):
            return
        self.values.append(float(x))
        self.ewma = (float(x) if self.ewma is None
                     else self.alpha * float(x)
                     + (1.0 - self.alpha) * self.ewma)
        self._memo = None

    def __len__(self) -> int:
        return len(self.values)

    @staticmethod
    def _median_sorted(xs: List[float]) -> float:
        n = len(xs)
        mid = n // 2
        return xs[mid] if n % 2 else 0.5 * (xs[mid - 1] + xs[mid])

    def _stats(self) -> Tuple[float, float]:
        if self._memo is None:
            xs = sorted(self.values)
            med = self._median_sorted(xs)
            mad = self._median_sorted(sorted(abs(v - med) for v in xs))
            self._memo = (med, max(1.4826 * mad,
                                   1e-3 * max(1.0, abs(med))))
        return self._memo

    def spread(self) -> float:
        """1.4826·MAD with a relative floor — a perfectly flat history must
        not turn the band into an equality test."""
        if not self.values:
            return float("inf")
        return self._stats()[1]

    def median(self) -> float:
        return self._stats()[0] if self.values else float("nan")

    def z(self, x: float) -> float:
        """Robust z of ``x`` against the current window (inf for nonfinite
        samples; 0 while the window is empty)."""
        if not math.isfinite(x):
            return float("inf")
        if not self.values:
            return 0.0
        return (float(x) - self.median()) / self.spread()

    def state_dict(self) -> Dict[str, Any]:
        return {"values": list(self.values), "ewma": self.ewma}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.values.clear()
        self.values.extend(float(v) for v in sd.get("values", []))
        self.ewma = sd.get("ewma")
        self._memo = None


# --------------------------------------------------------------- sentinel
class TrainingSentinel:
    """One engine's health sentinel. Wiring (``runtime/engine.py``):

    * ``offer_batch()`` — once per ``train_batch`` call, before any work:
      advances the stream position and answers whether this position is a
      journaled bad batch that must be skipped pre-dispatch (replay path).
    * ``gate_array()`` — the ``[loss_cap, grad_scale]`` rider injected into
      the batch dict under :data:`SENTINEL_GATE_KEY`.
    * ``at_step_boundary(global_steps, metrics)`` — from ``_post_step``:
      enqueue this step's device scalars, then drain every entry at least
      ``cfg.lag`` steps old (those have retired on device — the deferred
      ``device_get`` is the module's one sanctioned host sync).
    * ``note_checkpoint(tag, step, save_dir)`` — from the save path: the
      tag enters the promotion queue and becomes ``last_good`` once a
      healthy step ≥ ``step + cfg.last_good_k`` is observed.
    * ``state_dict()/load_state_dict()`` — rides checkpoint meta (position,
      window history, streaks) so resumes replay identical decisions;
      journaled bad positions are additionally re-read from the journal at
      construction, surviving restarts that predate the last save.

    ``exit_fn`` is injectable (default ``sys.exit``) so tests observe the
    rc-220 abort without dying."""

    def __init__(self, engine: Any, cfg: Any, rank: int = 0,
                 exit_fn: Optional[Callable[[int], None]] = None):
        self.engine = engine
        self.cfg = cfg
        self.rank = int(rank)
        self._exit_fn = exit_fn or sys.exit
        self._loss_stat = RobustStat(cfg.window, cfg.ewma_alpha)
        self._gn_stat = RobustStat(cfg.window, cfg.ewma_alpha)
        # (step, stream position, device-scalar refs) awaiting their lag
        self._pending: collections.deque = collections.deque()
        self._position = 0          # batches offered to train_batch so far
        self._bad_positions = set()  # journaled skip decisions, replayed
        self._healthy_steps = 0
        self._anomaly_streak = 0
        self._rollbacks = 0
        self._lr_cut_left = 0
        self._save_dir: Optional[str] = cfg.checkpoint_dir
        # promotion queue: tags waiting for K healthy steps beyond them
        self._pending_tags: List[Tuple[str, int]] = []
        self._promoted_step = -1
        self._journal_fh = None
        self._journal_path: Optional[str] = None
        self._resolve_journal()
        self._replay_journal()

    # ---------------------------------------------------------- journal
    def _resolve_journal(self) -> None:
        d = self.cfg.journal_dir
        if d is None and getattr(self.engine, "telemetry", None) is not None:
            d = self.engine.telemetry.cfg.output_dir
        if d is None:
            d = self._save_dir
        if d is None:
            return
        os.makedirs(d, exist_ok=True)
        self._journal_path = os.path.join(
            d, f"health_journal_rank{self.rank}.jsonl")

    def _replay_journal(self) -> None:
        """Re-read a pre-existing journal: skip decisions taken before a
        restart must survive it (the checkpoint meta only carries decisions
        old enough to have been saved)."""
        if self._journal_path is None or \
                not os.path.exists(self._journal_path):
            return
        n = 0
        with open(self._journal_path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail from a crash mid-append
                if rec.get("event") in ("skip", "nonfinite_skip") and \
                        rec.get("position") is not None:
                    self._bad_positions.add(int(rec["position"]))
                    n += 1
        if n:
            logger.info("sentinel: replaying %d journaled skip decision(s) "
                        "from %s", n, self._journal_path)

    def _journal(self, record: Dict[str, Any]) -> None:
        if self._journal_path is None:
            self._resolve_journal()
            if self._journal_path is None:
                return
        if self._journal_fh is None:
            self._journal_fh = open(self._journal_path, "a")
        self._journal_fh.write(json.dumps(record) + "\n")
        self._journal_fh.flush()

    def close(self) -> None:
        if self._journal_fh is not None:
            self._journal_fh.close()
            self._journal_fh = None

    # ------------------------------------------------------- step-path API
    def offer_batch(self) -> bool:
        """Advance the stream position; True ⇒ the engine must discard this
        batch *pre-dispatch* (a journaled skip being replayed after a
        rollback or restart)."""
        pos = self._position
        self._position += 1
        if pos in self._bad_positions:
            self._journal({"event": "skip_replay", "position": pos,
                           "step": self.engine.global_steps})
            return True
        return False

    def gate_array(self) -> np.ndarray:
        """Current ``[loss_cap, grad_scale]`` rider. The cap is the robust
        band's skip edge once warmed up (+inf before — but NaN losses still
        gate, NaN compares false); grad_scale is the transient post-rollback
        LR cut (1.0 otherwise)."""
        if len(self._loss_stat) >= self.cfg.warmup_steps:
            cap = (self._loss_stat.median()
                   + self.cfg.z_skip * self._loss_stat.spread())
        else:
            cap = float("inf")
        scale = self.cfg.lr_cut if self._lr_cut_left > 0 else 1.0
        return np.asarray([cap, scale], np.float32)

    def at_step_boundary(self, global_steps: int,
                         metrics: Dict[str, Any]) -> None:
        """Record this step's device scalars; process every pending step at
        least ``cfg.lag`` steps old (already retired on device)."""
        keep = {k: v for k, v in metrics.items()
                if k in ("loss", "grad_norm", "finite")
                or k.startswith("health_")}
        self._pending.append((global_steps, self._position - 1, keep))
        while self._pending and \
                self._pending[0][0] <= global_steps - self.cfg.lag:
            step, pos, m = self._pending.popleft()
            self._process(step, pos, m)

    # --------------------------------------------------------- the verdict
    def _process(self, step: int, pos: int, m: Dict[str, Any]) -> None:
        import jax

        vals = jax.device_get(m)
        loss = float(np.asarray(vals.get("loss", np.nan)))
        gn = float(np.asarray(vals.get("grad_norm", np.nan)))
        finite = bool(np.asarray(vals.get("finite", True)))
        nonfinite = int(np.asarray(vals.get("health_nonfinite", 0)))
        regions = {k[len("health_rn_"):]: float(np.asarray(v))
                   for k, v in vals.items() if k.startswith("health_rn_")}
        loss_z = self._loss_stat.z(loss)
        gn_z = self._gn_stat.z(gn)
        warmed = (len(self._loss_stat) >= self.cfg.warmup_steps)

        loss_bad = math.isnan(loss) or math.isinf(loss)
        if (not finite or nonfinite > 0) and not loss_bad and \
                getattr(self.engine, "fp16_enabled", False):
            # fp16 dynamic-loss-scale overflow (nonfinite grads under a
            # finite loss): the scaler already skipped the update and will
            # retry training at a lower scale — a *benign* event, but it
            # belongs in the same ledger ("overflow events unify into the
            # sentinel's ledger"). NOT a bad position: the scaler's skip is
            # itself deterministic, and replay-skipping the batch
            # pre-dispatch would desync the scaler trajectory from the
            # original run.
            self._record("overflow", step, pos, loss, loss_z, gn_z,
                         nonfinite, regions, skipped=False)
            return
        if nonfinite > 0 or not finite or loss_bad:
            worst = max(regions, key=regions.get) if regions else None
            self._anomaly(step, pos, "nonfinite", loss, loss_z, gn_z,
                          nonfinite, regions,
                          detail=f"nonfinite grads in region "
                                 f"{worst or '?'}" if nonfinite else
                                 "nonfinite loss")
            return
        if warmed and (loss_z > self.cfg.z_skip or gn_z > self.cfg.z_skip):
            self._anomaly(step, pos, "spike", loss, loss_z, gn_z,
                          nonfinite, regions,
                          detail=f"loss_z={loss_z:.1f} gn_z={gn_z:.1f}")
            return
        if warmed and (loss_z > self.cfg.z_warn or gn_z > self.cfg.z_warn):
            # warn rung: elevated but inside the skip band — surface it,
            # keep the sample (refusing it would freeze the band) and do
            # NOT advance the escalation streak
            self._record("warn", step, pos, loss, loss_z, gn_z, nonfinite,
                         regions, skipped=False)
        # healthy (or warned): feed history, settle streaks, promotions
        self._loss_stat.update(loss)
        self._gn_stat.update(gn)
        self._healthy_steps += 1
        self._anomaly_streak = 0
        if self._lr_cut_left > 0:
            self._lr_cut_left -= 1
        self._check_promotions(step)

    def _anomaly(self, step: int, pos: int, cause: str, loss: float,
                 loss_z: float, gn_z: float, nonfinite: int,
                 regions: Dict[str, float], detail: str = "") -> None:
        from ..monitor.monitor import resilience_counters

        self._anomaly_streak += 1
        self._bad_positions.add(pos)
        resilience_counters.incr("skipped_batches")
        logger.warning(
            "sentinel: step %d (stream position %d) unhealthy (%s%s); "
            "update was discarded in-graph, position journaled "
            "(streak %d/%d)", step, pos, cause,
            f": {detail}" if detail else "", self._anomaly_streak,
            self.cfg.skip_limit)
        self._record("skip", step, pos, loss, loss_z, gn_z, nonfinite,
                     regions, skipped=True, cause=cause)
        if self._anomaly_streak >= self.cfg.skip_limit:
            self._escalate(step, cause)

    def _record(self, action: str, step: int, pos: int, loss: float,
                loss_z: float, gn_z: float, nonfinite: int,
                regions: Dict[str, float], skipped: bool,
                cause: Optional[str] = None) -> None:
        rec = {"event": action, "step": step, "position": pos,
               "loss": None if math.isnan(loss) else loss,
               "loss_z": None if not math.isfinite(loss_z) else
               round(loss_z, 4),
               "grad_norm_z": None if not math.isfinite(gn_z) else
               round(gn_z, 4),
               "nonfinite": nonfinite}
        if cause:
            rec["cause"] = cause
        if skipped:
            rec["streak"] = self._anomaly_streak
        self._journal(rec)
        telemetry = getattr(self.engine, "telemetry", None)
        if telemetry is not None:
            telemetry.record_health(step, {
                "action": {"overflow": "skip"}.get(action, action),
                "cause": cause or action, "position": pos,
                "skipped": skipped,
                "loss_z": None if not math.isfinite(loss_z) else loss_z,
                "grad_norm_z": None if not math.isfinite(gn_z) else gn_z,
                "nonfinite": nonfinite, "streak": self._anomaly_streak,
                "region_norms": regions})

    # --------------------------------------------------------- escalation
    def _escalate(self, step: int, cause: str) -> None:
        if self._rollbacks >= self.cfg.rollback_limit or \
                self._save_dir is None or \
                getattr(self.engine, "_dataloader", None) is None:
            self._abort(step, cause)
            return
        self._rollback(step, cause)

    def _rollback(self, step: int, cause: str) -> None:
        from ..checkpoint.engine import find_last_good_tag
        from ..monitor.monitor import resilience_counters

        tag, skipped = find_last_good_tag(self._save_dir)
        if tag is None:
            logger.error("sentinel: no promoted last-good tag under %s "
                         "(skipped: %s) — cannot roll back", self._save_dir,
                         skipped)
            self._abort(step, cause)
            return
        t0 = time.perf_counter()
        logger.warning("sentinel: anomaly streak hit %d at step %d (%s); "
                       "rolling back to last-good tag %s",
                       self._anomaly_streak, step, cause, tag)
        bad = set(self._bad_positions)   # survive the meta restore below
        self._pending.clear()            # verdicts for a rewound future
        self._rollbacks += 1
        # load_checkpoint restores params/opt/scaler, global_steps, the
        # registered dataloader's position and this sentinel's saved state
        # (merged with `bad` in load_state_dict)
        self.engine.load_checkpoint(self._save_dir, tag)
        self._bad_positions |= bad
        self._anomaly_streak = 0
        self._lr_cut_left = self.cfg.lr_cut_steps
        rolled_to = self.engine.global_steps
        # drop queued promotions from the discarded future
        self._pending_tags = [(t, s) for t, s in self._pending_tags
                              if s <= rolled_to]
        dur = time.perf_counter() - t0
        resilience_counters.incr("rollbacks")
        telemetry = getattr(self.engine, "telemetry", None)
        if telemetry is not None:
            telemetry.goodput.account("rollback", dur)
            telemetry.record_health(rolled_to, {
                "action": "rollback", "cause": cause, "tag": tag,
                "rolled_back_to": rolled_to, "duration_s": round(dur, 3),
                "streak": 0})
        self._journal({"event": "rollback", "step": step,
                       "rolled_back_to": rolled_to, "tag": tag,
                       "cause": cause, "duration_s": round(dur, 3),
                       "lr_cut_steps": self._lr_cut_left})
        logger.warning("sentinel: rolled back to step %d (tag %s) in "
                       "%.2fs; %d journaled bad position(s) will be "
                       "skipped on replay", rolled_to, tag, dur,
                       len(self._bad_positions))

    def _abort(self, step: int, cause: str) -> None:
        from ..monitor.monitor import resilience_counters  # noqa: F401

        logger.error(
            "sentinel: divergence at step %d (%s) beyond the response "
            "ladder (rollbacks %d/%d); exiting with divergence code %d",
            step, cause, self._rollbacks, self.cfg.rollback_limit,
            DIVERGENCE_EXIT_CODE)
        try:
            # the scaler's overflow ledger joins the post-mortem record:
            # "the scale collapsed before the NaN" vs "healthy scaler, bad
            # data" is the first question the journal should answer
            from .loss_scaler import overflow_ledger

            scaler = overflow_ledger(self.engine.scaler_state)
        except Exception:  # host-offload scaler layouts etc.
            scaler = {}
        self._journal({"event": "abort", "step": step, "cause": cause,
                       "rollbacks": self._rollbacks, "scaler": scaler})
        telemetry = getattr(self.engine, "telemetry", None)
        if telemetry is not None:
            telemetry.record_health(step, {"action": "abort",
                                           "cause": cause})
            try:
                self.engine._flush_monitor()
                telemetry.dump("divergence")
            except Exception as e:  # observability never blocks the exit
                logger.warning("telemetry dump during divergence abort "
                               "failed: %s", e)
        self.close()
        self._exit_fn(DIVERGENCE_EXIT_CODE)

    # --------------------------------------------------------- promotions
    def note_checkpoint(self, tag: str, step: int, save_dir: str) -> None:
        """A checkpoint was written at ``step``: queue it for last-good
        promotion once ``cfg.last_good_k`` healthy steps beyond it are
        observed."""
        self._save_dir = save_dir
        if self.rank == 0:
            self._pending_tags.append((tag, int(step)))

    def _check_promotions(self, healthy_step: int) -> None:
        if not self._pending_tags or self._save_dir is None:
            return
        ripe = [(t, s) for t, s in self._pending_tags
                if healthy_step >= s + self.cfg.last_good_k]
        if not ripe:
            return
        self._pending_tags = [(t, s) for t, s in self._pending_tags
                              if healthy_step < s + self.cfg.last_good_k]
        tag, s = max(ripe, key=lambda ts: ts[1])
        if s <= self._promoted_step:
            return
        from ..checkpoint.engine import promote_last_good

        promote_last_good(self._save_dir, tag)
        self._promoted_step = s
        logger.info("sentinel: promoted %s (step %d) to last-good "
                    "(%d healthy steps beyond it)", tag, s,
                    healthy_step - s)

    # -------------------------------------------------------- persistence
    def state_dict(self) -> Dict[str, Any]:
        return {
            "position": self._position,
            "bad_positions": sorted(self._bad_positions),
            "healthy_steps": self._healthy_steps,
            "anomaly_streak": self._anomaly_streak,
            "rollbacks": self._rollbacks,
            "lr_cut_left": self._lr_cut_left,
            "promoted_step": self._promoted_step,
            "pending_tags": [list(ts) for ts in self._pending_tags],
            "loss_stat": self._loss_stat.state_dict(),
            "gn_stat": self._gn_stat.state_dict(),
        }

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self._position = int(sd.get("position", 0))
        # UNION, not replace: skips journaled after the checkpoint was
        # written must survive the rollback that restores it
        self._bad_positions |= {int(p) for p in sd.get("bad_positions", [])}
        self._healthy_steps = int(sd.get("healthy_steps", 0))
        self._anomaly_streak = int(sd.get("anomaly_streak", 0))
        # NOT restored: self._rollbacks — the abort ladder counts rollbacks
        # per process lifetime, and restoring the saved (pre-rollback) count
        # would reset the budget every time a rollback loads a checkpoint
        self._lr_cut_left = int(sd.get("lr_cut_left", 0))
        self._promoted_step = max(self._promoted_step,
                                  int(sd.get("promoted_step", -1)))
        self._pending_tags = [(str(t), int(s))
                              for t, s in sd.get("pending_tags", [])]
        self._loss_stat.load_state_dict(sd.get("loss_stat", {}))
        self._gn_stat.load_state_dict(sd.get("gn_stat", {}))
