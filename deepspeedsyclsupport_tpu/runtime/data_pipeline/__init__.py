"""Data-efficiency pipeline: curriculum learning, data sampling, random-LTD.

Analog of ``deepspeed/runtime/data_pipeline/`` (2177 LoC):

* ``CurriculumScheduler`` (``curriculum_scheduler.py:11``) — difficulty
  schedules ``fixed_linear`` / ``fixed_root`` / ``fixed_discrete`` /
  ``custom``, same config keys (``min_difficulty``, ``max_difficulty``,
  ``schedule_type``, ``schedule_config{total_curriculum_step,
  difficulty_step, root_degree | difficulty, max_step}``).
* ``CurriculumDataSampler`` — the ``data_sampling/data_sampler.py`` analog:
  difficulty-gated index sampling over per-sample metric values
  (value- or percentile-based, reference ``CURRICULUM_LEARNING_
  {VALUE,PERCENTILE}_BASED``), deterministic per-epoch shuffle.
* ``RandomLTDScheduler`` (``data_routing/scheduler.py``) — scheduled
  kept-token count for random layerwise token dropping; the token
  gather/scatter the reference does in ``csrc/random_ltd/`` is jnp
  ``take_along_axis``/``.at[].set`` inside the model
  (``models/transformer.py``), which XLA fuses.

TPU note: difficulty changes the *shape* of the compiled program (seqlen or
kept-token count), so each difficulty level compiles once. The reference
quantizes levels with ``difficulty_step`` for tensor cores; here the same
knob bounds the number of XLA compilations.
"""
import math
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np

from ...utils.logging import logger

# config keys — reference data_pipeline/constants.py
FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"
VALUE_BASED = "value"
PERCENTILE_BASED = "percentile"


class CurriculumScheduler:
    """Difficulty schedule (reference ``curriculum_scheduler.py:11``)."""

    def __init__(self, config: Dict[str, Any]):
        for key in ("min_difficulty", "max_difficulty", "schedule_type"):
            if key not in config:
                raise ValueError(f"curriculum learning requires '{key}'")
        self.min_difficulty = int(config["min_difficulty"])
        self.max_difficulty = int(config["max_difficulty"])
        self.schedule_type = config["schedule_type"]
        self.schedule = dict(config.get("schedule_config", {}))
        self.current_difficulty = self.min_difficulty
        self._custom_fn = config.get("difficulty_fn")

        if self.schedule_type in (FIXED_LINEAR, FIXED_ROOT):
            for key in ("total_curriculum_step", "difficulty_step"):
                if key not in self.schedule:
                    raise ValueError(
                        f"{self.schedule_type} schedule requires "
                        f"schedule_config '{key}'")
            if self.schedule_type == FIXED_ROOT and \
                    "root_degree" not in self.schedule:
                raise ValueError("fixed_root requires 'root_degree'")
        elif self.schedule_type == FIXED_DISCRETE:
            diff = self.schedule.get("difficulty")
            steps = self.schedule.get("max_step")
            if not diff or steps is None or len(diff) != len(steps) + 1:
                raise ValueError(
                    "fixed_discrete needs schedule_config 'difficulty' (n) "
                    "and 'max_step' (n-1)")
        elif self.schedule_type == CUSTOM:
            if not callable(self._custom_fn):
                raise ValueError("custom schedule requires a callable "
                                 "'difficulty_fn' in the config")
        else:
            raise ValueError(f"unknown schedule_type {self.schedule_type!r}")

    def get_difficulty(self, global_steps: int) -> int:
        if self.schedule_type == FIXED_DISCRETE:
            for d, until in zip(self.schedule["difficulty"],
                                self.schedule["max_step"]):
                if global_steps <= until:
                    return int(d)
            return int(self.schedule["difficulty"][-1])
        if self.schedule_type == CUSTOM:
            return int(self._custom_fn(global_steps))
        total = int(self.schedule["total_curriculum_step"])
        step_q = int(self.schedule["difficulty_step"])
        frac = min(1.0, max(0.0, global_steps / max(total, 1)))
        if self.schedule_type == FIXED_ROOT:
            frac = frac ** (1.0 / float(self.schedule["root_degree"]))
        raw = self.min_difficulty + frac * (self.max_difficulty -
                                            self.min_difficulty)
        d = int(raw // step_q) * step_q
        return int(min(max(d, self.min_difficulty), self.max_difficulty))

    def update_difficulty(self, global_steps: int) -> int:
        self.current_difficulty = self.get_difficulty(global_steps)
        return self.current_difficulty

    # checkpointable state (reference state dict protocol)
    def state_dict(self) -> Dict[str, Any]:
        return {"current_difficulty": self.current_difficulty}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.current_difficulty = int(sd["current_difficulty"])


def truncate_to_difficulty(batch, difficulty: int):
    """Seqlen-metric curriculum: clip every [B, S, ...] leaf to S' =
    ``difficulty`` along dim 1 (reference legacy curriculum truncation used
    by megatron integration). Shorter-than-difficulty batches pass through."""
    import jax

    def clip(x):
        if getattr(x, "ndim", 0) >= 2 and x.shape[1] > difficulty:
            return x[:, :difficulty]
        return x

    return jax.tree_util.tree_map(clip, batch)


class CurriculumDataSampler:
    """Difficulty-gated batch sampler (``data_sampling/data_sampler.py``
    ``DeepSpeedDataSampler`` analog).

    ``metric_values[i]`` scores sample ``i`` (e.g. sequence length); a batch
    at step ``t`` draws only from samples whose metric is within the
    scheduler's current difficulty — by value, or by percentile of the
    metric distribution (reference difficulty_type value/percentile).
    """

    def __init__(self, metric_values: Sequence[float], batch_size: int,
                 scheduler: CurriculumScheduler,
                 difficulty_type: str = VALUE_BASED,
                 seed: int = 1234, drop_last: bool = True):
        self.metric = np.asarray(metric_values, np.float64)
        self.order = np.argsort(self.metric, kind="stable")  # easy → hard
        self.batch_size = int(batch_size)
        self.scheduler = scheduler
        self.difficulty_type = difficulty_type
        if difficulty_type not in (VALUE_BASED, PERCENTILE_BASED):
            raise ValueError(f"unknown difficulty_type {difficulty_type!r}")
        self.seed = seed
        self.drop_last = drop_last
        self.global_step = 0
        self.epoch = 0

    def _eligible(self) -> np.ndarray:
        d = self.scheduler.update_difficulty(self.global_step)
        if self.difficulty_type == VALUE_BASED:
            n = int(np.searchsorted(self.metric[self.order], d, side="right"))
        else:  # percentile of samples admitted
            n = int(math.ceil(len(self.metric) * min(d, 100) / 100.0))
        return self.order[:max(n, self.batch_size if self.drop_last else 1)]

    def __iter__(self) -> Iterator[np.ndarray]:
        rng = np.random.default_rng(self.seed + self.epoch)
        n_batches = len(self.metric) // self.batch_size
        for _ in range(n_batches):
            pool = self._eligible()
            idx = rng.choice(pool, size=self.batch_size,
                             replace=len(pool) < self.batch_size)
            self.global_step += 1
            yield idx
        self.epoch += 1

    def state_dict(self):
        return {"global_step": self.global_step, "epoch": self.epoch}

    def load_state_dict(self, sd):
        self.global_step = int(sd["global_step"])
        self.epoch = int(sd["epoch"])


class RandomLTDScheduler:
    """Kept-token schedule for random layerwise token dropping (reference
    ``data_routing/scheduler.py`` RandomLTDScheduler; kernels
    ``csrc/random_ltd/``). Value = number of tokens the middle layers keep;
    rises from ``min_value`` to ``max_value`` (full sequence) by
    ``seq_per_step`` every ``require_steps`` steps (fixed_linear)."""

    def __init__(self, config: Dict[str, Any]):
        self.min_value = int(config["min_value"])
        self.max_value = int(config["max_value"])
        sched = dict(config.get("schedule_config", {}))
        self.seq_per_step = int(sched.get("seq_per_step", 16))
        self.require_steps = int(sched.get("require_steps", 100))
        self.schedule_type = config.get("schedule_type", FIXED_LINEAR)
        if self.schedule_type != FIXED_LINEAR:
            raise ValueError("random-ltd supports fixed_linear schedules")
        self.current_value = self.min_value

    def get_value(self, global_steps: int) -> int:
        inc = (global_steps // max(self.require_steps, 1)) * self.seq_per_step
        self.current_value = int(min(self.min_value + inc, self.max_value))
        return self.current_value

    def state_dict(self):
        return {"current_value": self.current_value}

    def load_state_dict(self, sd):
        self.current_value = int(sd["current_value"])
