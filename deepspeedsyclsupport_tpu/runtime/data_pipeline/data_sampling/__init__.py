from .data_analyzer import DataAnalyzer, DifficultyIndex
from .data_sampler import DSTpuDataSampler
from .indexed_dataset import (MMapIndexedDataset, MMapIndexedDatasetBuilder,
                              data_file_path, index_file_path, make_dataset)

__all__ = [
    "MMapIndexedDataset", "MMapIndexedDatasetBuilder", "make_dataset",
    "data_file_path", "index_file_path", "DSTpuDataSampler", "DataAnalyzer",
    "DifficultyIndex",
]
