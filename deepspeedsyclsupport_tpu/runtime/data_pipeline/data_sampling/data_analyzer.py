"""Offline data analysis → per-sample difficulty index.

Analog of the reference's ``data_sampling/data_analyzer.py`` (DataAnalyzer:
map a metric function over the corpus, write metric↔sample index files the
curriculum sampler reads). Here the product is a :class:`DifficultyIndex` —
per-sample metric values plus the ascending-difficulty permutation — saved
as plain ``.npy`` files instead of nested indexed datasets: the sampler
needs exactly (value per sample, sort order), and numpy files keep the
artifact inspectable.
"""
import os
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np


@dataclass
class DifficultyIndex:
    """values[i] = metric of sample i; order = sample ids sorted ascending
    by (metric, id) — id tiebreak keeps the permutation deterministic."""
    values: np.ndarray
    order: np.ndarray

    def save(self, prefix: str) -> None:
        os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)
        np.save(prefix + "_metric_values.npy", self.values)
        np.save(prefix + "_metric_order.npy", self.order)

    @classmethod
    def load(cls, prefix: str) -> "DifficultyIndex":
        return cls(values=np.load(prefix + "_metric_values.npy"),
                   order=np.load(prefix + "_metric_order.npy"))

    def pool_leq_value(self, difficulty) -> np.ndarray:
        """Sample ids whose metric <= difficulty (value-based curriculum)."""
        # order is metric-ascending: binary-search the cut
        cut = np.searchsorted(self.values[self.order], difficulty,
                              side="right")
        return self.order[:cut]

    def pool_percentile(self, pct: float) -> np.ndarray:
        """The easiest ``pct`` percent of samples (percentile-based)."""
        cut = max(1, int(len(self.order) * min(max(pct, 0.0), 100.0) / 100))
        return self.order[:cut]


class DataAnalyzer:
    """Map ``metric_fn(sample) -> number`` over an indexed dataset
    (reference ``DataAnalyzer.run_map``). Default metric is sequence length
    — the curriculum the reference's seqlen_* metrics implement — read
    straight from the index's ``sizes`` without touching the ``.bin``."""

    def __init__(self, metric_fn: Optional[Callable] = None,
                 metric_name: str = "seqlen"):
        self.metric_fn = metric_fn
        self.metric_name = metric_name

    def run(self, dataset, save_prefix: Optional[str] = None
            ) -> DifficultyIndex:
        if self.metric_fn is None and hasattr(dataset, "sizes"):
            values = np.asarray(dataset.sizes)
        else:
            fn = self.metric_fn or len
            values = np.asarray([fn(dataset[i])
                                 for i in range(len(dataset))])
        order = np.lexsort((np.arange(len(values)), values))
        idx = DifficultyIndex(values=values, order=order.astype(np.int64))
        if save_prefix is not None:
            idx.save(save_prefix)
        return idx
