"""Curriculum data sampler over indexed datasets.

Analog of ``DeepSpeedDataSampler``
(``data_sampling/data_sampler.py:36``): every global step, draw the global
batch's sample ids from the pool the curriculum currently allows (metric
value or percentile threshold from a :class:`CurriculumScheduler`), shuffle
deterministically, and hand THIS data-parallel rank its slice. Differences
from the reference are deliberate: pools come from a
:class:`~.data_analyzer.DifficultyIndex` (binary-searched, no cluster
files), and the draw is a pure function of (seed, step) so resume needs no
replay — ``state_dict`` is just the step/consumed counters.
"""
from typing import Dict, Iterator, Optional

import numpy as np

from .. import CurriculumScheduler
from .data_analyzer import DifficultyIndex


class DSTpuDataSampler:
    def __init__(self, index: DifficultyIndex,
                 curriculum: Optional[Dict] = None, *,
                 micro_batch_size: int,
                 data_parallel_rank: int, data_parallel_size: int,
                 gradient_accumulation_steps: int = 1,
                 difficulty_type: str = "value",
                 total_steps: Optional[int] = None,
                 seed: int = 1234, drop_last: bool = True):
        """``curriculum``: a reference-style schedule config (the
        ``CurriculumScheduler`` dict: schedule_type/min/max/...); None
        disables gating (the full corpus from step 0).
        ``difficulty_type``: 'value' (metric <= difficulty) or 'percentile'
        (easiest d% of the corpus) — reference
        CURRICULUM_LEARNING_DIFFICULTY_TYPE."""
        if difficulty_type not in ("value", "percentile"):
            raise ValueError(f"unknown difficulty_type {difficulty_type!r}")
        self.index = index
        self.scheduler = (CurriculumScheduler(curriculum)
                          if curriculum is not None else None)
        self.micro_batch_size = micro_batch_size
        self.dp_rank = data_parallel_rank
        self.dp_size = data_parallel_size
        self.gas = gradient_accumulation_steps
        self.global_batch_size = (micro_batch_size * data_parallel_size
                                  * gradient_accumulation_steps)
        self.difficulty_type = difficulty_type
        self.total_steps = total_steps
        self.seed = seed
        self.drop_last = drop_last
        self.step = 0
        self.consumed_samples = 0
        if data_parallel_rank >= data_parallel_size:
            raise ValueError(f"dp rank {data_parallel_rank} outside world "
                             f"{data_parallel_size}")

    # ------------------------------------------------------------------ pool
    def _pool(self, step: int) -> np.ndarray:
        if self.scheduler is None:
            return self.index.order
        d = self.scheduler.update_difficulty(step)
        self.current_difficulty = d
        pool = (self.index.pool_leq_value(d)
                if self.difficulty_type == "value"
                else self.index.pool_percentile(float(d)))
        if len(pool) == 0:
            # an over-strict early threshold must not wedge training: fall
            # back to the easiest micro-batch worth of samples
            pool = self.index.order[:self.global_batch_size]
        return pool

    def batch_for_step(self, step: int) -> np.ndarray:
        """This rank's sample ids for global step ``step``, shaped
        [gas, micro_batch_size]. Pure in (seed, step): every rank computes
        the same global permutation and slices its own rows (the
        reference's get_start_end_idx contract)."""
        pool = self._pool(step)
        rng = np.random.default_rng((self.seed, step))
        need = self.global_batch_size
        if len(pool) >= need:
            # epoch-position draw WITHIN the pool: step-scoped shuffle
            picks = rng.choice(len(pool), size=need, replace=False)
        else:
            picks = rng.integers(0, len(pool), size=need)
        ids = pool[np.sort(picks)]
        ids = rng.permutation(ids)
        mine = ids.reshape(self.gas, self.dp_size, self.micro_batch_size)
        return mine[:, self.dp_rank, :]

    def __iter__(self) -> Iterator[np.ndarray]:
        while self.total_steps is None or self.step < self.total_steps:
            out = self.batch_for_step(self.step)
            self.step += 1
            self.consumed_samples += self.global_batch_size
            yield out

    def __len__(self) -> int:
        if self.total_steps is None:
            raise TypeError("unbounded sampler (total_steps=None)")
        return self.total_steps

    # ----------------------------------------------------------------- state
    def state_dict(self) -> Dict:
        return {"step": self.step, "consumed_samples": self.consumed_samples,
                "seed": self.seed}

    def load_state_dict(self, state: Dict) -> None:
        self.step = int(state["step"])
        self.consumed_samples = int(state["consumed_samples"])
        self.seed = int(state.get("seed", self.seed))


class IndexedTokenBatches:
    """Glue: (indexed dataset, sampler) → fixed-shape token batches for
    ``DSTpuDataLoader`` / ``engine.train_batch``. Samples pad (with
    ``pad_id``) or clip to ``seq_len``; each iteration yields
    ``{"input_ids": int32 [gas*micro_batch, seq_len]}`` for this rank."""

    def __init__(self, dataset, sampler: DSTpuDataSampler, seq_len: int,
                 pad_id: int = 0):
        self.dataset = dataset
        self.sampler = sampler
        self.seq_len = seq_len
        self.pad_id = pad_id

    def __len__(self) -> int:
        return len(self.sampler)

    def __iter__(self):
        for ids in self.sampler:
            flat = ids.reshape(-1)
            batch = np.full((len(flat), self.seq_len), self.pad_id, np.int32)
            for row, sid in enumerate(flat):
                toks = np.asarray(self.dataset[int(sid)])[:self.seq_len]
                batch[row, :len(toks)] = toks
            yield {"input_ids": batch}
