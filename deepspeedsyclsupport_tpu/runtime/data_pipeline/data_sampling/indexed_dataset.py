"""Memory-mapped indexed datasets — the Megatron ``.bin``/``.idx`` format.

Analog of the reference's
``deepspeed/runtime/data_pipeline/data_sampling/indexed_dataset.py:369``
(``MMapIndexedDataset`` + builder): token corpora pre-tokenized into one
flat binary file plus an index of per-sample sizes/offsets, read back with
zero-copy ``np.memmap``. The ON-DISK FORMAT is kept bit-compatible with
Megatron-LM / DeepSpeed exports (same magic, codes, layout) so existing
preprocessed corpora load unmodified; the implementation is original and
torch-free (plain numpy — samples feed ``DSTpuDataLoader`` which owns
device placement).

Index layout (little-endian)::

    9s  magic  b"MMIDIDX\\x00\\x00"
    Q   version (1)
    B   dtype code (see DTYPES)
    Q   number of samples
    Q   number of document positions
    int32  sizes[n_samples]        tokens per sample
    int64  pointers[n_samples]     byte offset of each sample in the .bin
    int64  doc_idx[n_docs]         sample index of each document start
"""
import os
import struct
from typing import Optional, Sequence, Union

import numpy as np

_MAGIC = b"MMIDIDX\x00\x00"
_VERSION = 1

# dtype codes of the format (indexed_dataset.py:101 in the reference)
DTYPES = {
    1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
    6: np.float64, 7: np.float64, 8: np.uint16, 9: np.uint32, 10: np.uint64,
}
_CODES = {np.dtype(v): k for k, v in reversed(sorted(DTYPES.items()))}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDataset:
    """Zero-copy reader. ``ds[i]`` → the i-th sample as a numpy view;
    ``ds[a:b]`` → list of samples; ``ds.get(i, offset, length)`` → a slice
    of one sample (the reference's partial-read API)."""

    def __init__(self, path_prefix: str):
        self._prefix = path_prefix
        with open(index_file_path(path_prefix), "rb") as f:
            magic = f.read(9)
            if magic != _MAGIC:
                raise ValueError(
                    f"{index_file_path(path_prefix)}: not an MMIDIDX index "
                    f"(bad magic {magic!r})")
            (version,) = struct.unpack("<Q", f.read(8))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            (code,) = struct.unpack("<B", f.read(1))
            self.dtype = np.dtype(DTYPES[code])
            (n, ) = struct.unpack("<Q", f.read(8))
            (n_docs, ) = struct.unpack("<Q", f.read(8))
            header_end = f.tell()
        idx = np.memmap(index_file_path(path_prefix), mode="r", order="C")
        off = header_end
        self.sizes = np.frombuffer(idx, np.int32, count=n, offset=off)
        off += n * 4
        self._pointers = np.frombuffer(idx, np.int64, count=n, offset=off)
        off += n * 8
        self.doc_idx = np.frombuffer(idx, np.int64, count=n_docs, offset=off)
        self._bin = np.memmap(data_file_path(path_prefix), mode="r",
                              dtype=self.dtype, order="C")

    def __len__(self) -> int:
        return len(self.sizes)

    def __getitem__(self, i: Union[int, slice]):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if i < 0:
            i += len(self)
        start = self._pointers[i] // self.dtype.itemsize
        return self._bin[start:start + self.sizes[i]]

    def get(self, i: int, offset: int = 0,
            length: Optional[int] = None) -> np.ndarray:
        """Partial sample read (reference ``MMapIndexedDataset.get``)."""
        size = int(self.sizes[i])
        if length is None:
            length = size - offset
        if offset < 0 or offset + length > size:
            raise IndexError(f"slice [{offset}:{offset + length}] outside "
                             f"sample {i} of size {size}")
        start = self._pointers[i] // self.dtype.itemsize + offset
        return self._bin[start:start + length]

    @property
    def supports_prefetch(self) -> bool:
        return False  # mmap pages on demand

    @staticmethod
    def exists(path_prefix: str) -> bool:
        return (os.path.exists(index_file_path(path_prefix))
                and os.path.exists(data_file_path(path_prefix)))


class MMapIndexedDatasetBuilder:
    """Streaming writer (reference ``MMapIndexedDatasetBuilder:575``):
    ``add_item`` per sample, ``end_document`` at document boundaries,
    ``finalize`` writes the index."""

    def __init__(self, out_file: str, dtype=np.int32):
        self._bin = open(out_file, "wb")
        self.dtype = np.dtype(dtype)
        self._sizes = []
        self._doc_idx = [0]

    def add_item(self, tokens: Sequence) -> None:
        arr = np.asarray(tokens, dtype=self.dtype)
        self._bin.write(arr.tobytes(order="C"))
        self._sizes.append(len(arr))

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def add_dataset(self, other: "MMapIndexedDataset") -> None:
        """Merge another indexed dataset (the reference's merge path for
        sharded preprocessing jobs)."""
        if other.dtype != self.dtype:
            raise ValueError(f"dtype mismatch: {other.dtype} vs {self.dtype}")
        base = len(self._sizes)
        for i in range(len(other)):
            self.add_item(other[i])
        self._doc_idx.extend(base + d for d in other.doc_idx[1:])

    def finalize(self, index_file: str) -> None:
        self._bin.close()
        sizes = np.asarray(self._sizes, np.int32)
        pointers = np.zeros(len(sizes), np.int64)
        if len(sizes) > 1:
            np.cumsum(sizes[:-1] * self.dtype.itemsize, out=pointers[1:])
        with open(index_file, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<Q", _VERSION))
            f.write(struct.pack("<B", _CODES[self.dtype]))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(struct.pack("<Q", len(self._doc_idx)))
            f.write(sizes.tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))
            f.write(np.asarray(self._doc_idx, np.int64).tobytes(order="C"))


def make_dataset(path_prefix: str, impl: str = "mmap") -> MMapIndexedDataset:
    """Factory (reference ``make_dataset``): only the mmap impl exists here —
    the reference's ``cached``/``lazy`` variants predate it and are
    deprecated upstream."""
    if impl not in ("mmap", "infer"):
        raise ValueError(f"unsupported indexed dataset impl {impl!r} "
                         f"(mmap only)")
    if not MMapIndexedDataset.exists(path_prefix):
        raise FileNotFoundError(f"no indexed dataset at {path_prefix}"
                                f"(.bin/.idx)")
    return MMapIndexedDataset(path_prefix)
