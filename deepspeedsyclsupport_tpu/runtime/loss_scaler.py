"""fp16 loss scaling.

Analog of ``deepspeed/runtime/fp16/loss_scaler.py`` (``LossScaler`` static,
``DynamicLossScaler`` with scale window/hysteresis) used by ``FP16_Optimizer``
(``runtime/fp16/fused_optimizer.py:31``).

Functional design: the scaler is an immutable pytree threaded through the jitted
train step. Overflow check = non-finite grads; on overflow the step is skipped
(grads zeroed, optimizer state untouched) and the scale halves after ``hysteresis``
consecutive overflows; after ``scale_window`` clean steps it doubles — the exact
reference policy, but branch-free under jit via ``jnp.where``.
"""
from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class LossScaleState(NamedTuple):
    scale: jnp.ndarray          # current loss scale (f32 scalar)
    good_steps: jnp.ndarray     # consecutive overflow-free steps (i32)
    hysteresis_left: jnp.ndarray  # remaining tolerated overflows before halving (i32)
    overflows: jnp.ndarray      # cumulative skipped steps (i32)


def init_loss_scale(initial_scale: float, dynamic: bool, hysteresis: int = 2) -> LossScaleState:
    return LossScaleState(
        scale=jnp.asarray(initial_scale, jnp.float32),
        good_steps=jnp.zeros((), jnp.int32),
        hysteresis_left=jnp.asarray(hysteresis if dynamic else 2**30, jnp.int32),
        overflows=jnp.zeros((), jnp.int32),
    )


def grads_finite(grads) -> jnp.ndarray:
    """Global overflow check (reference: ``CHECK_OVERFLOW``/``has_overflow`` paths —
    there a device-wide allreduce of an inf flag; here a tree-reduce XLA fuses)."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.asarray(True)
    finites = [jnp.all(jnp.isfinite(g)) for g in leaves]
    return jnp.stack(finites).all()


def update_loss_scale(state: LossScaleState, finite: jnp.ndarray, *,
                      dynamic: bool, scale_window: int, scale_factor: float = 2.0,
                      min_scale: float = 1.0, hysteresis: int = 2) -> LossScaleState:
    """One scaler transition (reference ``DynamicLossScaler.update_scale``)."""
    if not dynamic:
        return state._replace(overflows=state.overflows + (~finite).astype(jnp.int32))

    # overflow path: consume hysteresis; halve scale when exhausted
    hys = jnp.where(finite, state.hysteresis_left, state.hysteresis_left - 1)
    halve = (~finite) & (hys <= 0)
    new_scale = jnp.where(halve, jnp.maximum(state.scale / scale_factor, min_scale),
                          state.scale)
    hys = jnp.where(halve, hysteresis, hys)

    # clean-window path: double scale every `scale_window` good steps
    good = jnp.where(finite, state.good_steps + 1, 0)
    grow = finite & (good >= scale_window)
    new_scale = jnp.where(grow, new_scale * scale_factor, new_scale)
    good = jnp.where(grow, 0, good)
    hys = jnp.where(grow, hysteresis, hys)

    return LossScaleState(
        scale=new_scale,
        good_steps=good.astype(jnp.int32),
        hysteresis_left=hys.astype(jnp.int32),
        overflows=state.overflows + (~finite).astype(jnp.int32),
    )


def host_loss_scale_state(state: LossScaleState) -> LossScaleState:
    """Host-resident (numpy) copy of a scaler state. The offloaded CPU
    optimizer runs the scale state machine entirely on host, so its
    per-step scale reads must be plain floats — pulling a device scalar
    every step is exactly the host sync the step path must not pay. Called
    at engine init / checkpoint load (both sanctioned sync sites), never
    per step."""
    return LossScaleState(*(np.asarray(v) for v in state))


def host_update_loss_scale(state: LossScaleState, finite: bool, *,
                           dynamic: bool, scale_window: int,
                           scale_factor: float = 2.0, min_scale: float = 1.0,
                           hysteresis: int = 2) -> LossScaleState:
    """:func:`update_loss_scale` for host (numpy) state: the identical
    transition in plain Python arithmetic, so the offloaded step performs
    zero device work for loss scaling. Kept in lockstep with the jnp
    version — the multi-process parity test compares the two paths'
    trajectories bit-for-bit."""
    finite = bool(finite)
    overflows = np.int32(int(state.overflows) + (0 if finite else 1))
    if not dynamic:
        return state._replace(overflows=overflows)
    scale = float(state.scale)
    good = int(state.good_steps)
    hys = int(state.hysteresis_left)
    if finite:
        good += 1
        if good >= scale_window:
            scale *= scale_factor
            good = 0
            hys = hysteresis
    else:
        hys -= 1
        if hys <= 0:
            scale = max(scale / scale_factor, min_scale)
            hys = hysteresis
        good = 0
    return LossScaleState(scale=np.float32(scale), good_steps=np.int32(good),
                          hysteresis_left=np.int32(hys), overflows=overflows)


def overflow_ledger(state: LossScaleState) -> dict:
    """Host-side snapshot of the scaler's overflow bookkeeping for the
    training sentinel's unified health ledger (``runtime/sentinel.py``): the
    scaler's skip-on-inf events and the sentinel's spike/NaN skips are the
    same phenomenon at different severities, and the journal reports them
    side by side. Forces a device read — call from sanctioned sites only
    (checkpoint meta, divergence abort), never per step (the sentinel's
    per-step view rides the ``finite`` metric it already fetches)."""
    return {"overflows": int(np.asarray(state.overflows)),
            "scale": float(np.asarray(state.scale)),
            "good_steps": int(np.asarray(state.good_steps))}


def scale_loss(loss, state: LossScaleState):
    return loss * state.scale.astype(loss.dtype)


def unscale_grads(grads, state: LossScaleState):
    inv = (1.0 / state.scale).astype(jnp.float32)
    return jax.tree_util.tree_map(lambda g: g.astype(jnp.float32) * inv, grads)
