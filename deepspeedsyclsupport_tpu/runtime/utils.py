"""Runtime utility surface (reference ``deepspeed/runtime/utils.py``).

The functions user scripts actually import when porting: memory
reporting, global-norm/clipping helpers, seeding, small conveniences.
JAX shift: tensors are immutable, so the ``_``-suffixed in-place
clippers return NEW trees (callers must rebind); device "cache" memory
is XLA-managed, so ``empty_cache`` is a documented no-op.
"""
import os
import random
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import log_dist
# re-exports: the reference keeps these in runtime/utils.py too
from ..parallel.pipeline import (partition_balanced,  # noqa: F401
                                 partition_uniform)

__all__ = [
    "see_memory_usage", "memory_status", "get_ma_status", "empty_cache",
    "set_random_seed", "ensure_directory_exists", "noop_decorator",
    "call_to_str", "get_only_unique_item", "get_global_norm",
    "get_global_norm_of_tensors", "get_grad_norm", "get_weight_norm",
    "clip_grad_norm_", "clip_gradients", "clip_tensors_by_global_norm",
    "partition_uniform", "partition_balanced", "get_inactive_params",
]


# ---------------------------------------------------------------- memory
def get_ma_status(device=None) -> Dict[str, int]:
    """Device memory stats (reference ``get_ma_status`` returns torch's
    memory_allocated; here XLA's per-device stats dict)."""
    dev = device or jax.devices()[0]
    try:
        return dict(dev.memory_stats() or {})
    except Exception:
        return {}


def memory_status(msg: str = "", device=None) -> Dict[str, int]:
    """Log + return device memory stats (reference ``memory_status``)."""
    stats = get_ma_status(device)
    used = stats.get("bytes_in_use", 0)
    peak = stats.get("peak_bytes_in_use", used)
    limit = stats.get("bytes_limit", 0)
    log_dist(f"memory_status {msg}: in_use={used / 2**30:.2f}GB "
             f"peak={peak / 2**30:.2f}GB limit={limit / 2**30:.2f}GB")
    return stats


def see_memory_usage(message: str, force: bool = False) -> None:
    """Reference ``see_memory_usage``: device + host memory snapshot.
    ``force=False`` is a no-op (same gating as the reference)."""
    if not force:
        return
    stats = get_ma_status()
    used = stats.get("bytes_in_use", 0)
    peak = stats.get("peak_bytes_in_use", used)
    try:
        import resource

        host_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
    except Exception:
        host_mb = 0.0
    log_dist(f"{message} | device MA {used / 2**30:.2f} GB, peak "
             f"{peak / 2**30:.2f} GB | host RSS peak {host_mb / 1024:.2f} GB")


def empty_cache() -> None:
    """Reference ``empty_cache`` (torch.cuda.empty_cache). Deliberately a
    no-op: XLA owns device allocation (live buffers free when their arrays
    drop), and scripts call this inside training loops — clearing the jit
    cache here would force a full recompile per call. To actually drop
    compiled programs, call ``jax.clear_caches()`` yourself."""


# ----------------------------------------------------------------- misc
def set_random_seed(seed: int) -> None:
    """Reference ``set_random_seed``: python + numpy. JAX randomness is
    explicit-key (pass ``jax.random.PRNGKey(seed)`` to the engine/model);
    there is deliberately no hidden global to seed."""
    random.seed(seed)
    np.random.seed(seed)


def ensure_directory_exists(filename: str) -> None:
    """Reference ``ensure_directory_exists`` — mkdir -p of the dirname."""
    d = os.path.dirname(filename)
    if d:
        os.makedirs(d, exist_ok=True)


def noop_decorator(func):
    return func


def call_to_str(base: str, *args, **kwargs) -> str:
    """Reference ``call_to_str``: render a call for logging."""
    name = f"{base}("
    if args:
        name += ", ".join(repr(arg) for arg in args)
        if kwargs:
            name += ", "
    if kwargs:
        name += ", ".join(f"{k}={v!r}" for k, v in kwargs.items())
    return name + ")"


def get_only_unique_item(items: Sequence) -> Any:
    found = set(items)
    if len(found) != 1:
        raise RuntimeError(f"expected there to be only one unique element "
                           f"in {items}")
    return next(iter(found))


def get_inactive_params(params) -> List:
    """Reference ``get_inactive_params`` (ZeRO-3 NOT_AVAILABLE partitioned
    params). GSPMD keeps every leaf logically available — sharded arrays
    are never 'inactive' — so this is always empty, by design."""
    return []


# ------------------------------------------------------- norms / clipping
def get_global_norm_of_tensors(tensors, norm_type: float = 2.0,
                               mpu=None, use_graph=False,
                               moe_ep_group=None):
    """Global norm over a list/pytree (reference
    ``get_global_norm_of_tensors``). ``mpu``/groups are accepted for
    signature parity and unused: norms over GLOBAL jax arrays already span
    every shard, which is the whole job the reference's mpu reductions
    do."""
    del mpu, use_graph, moe_ep_group
    leaves = jax.tree_util.tree_leaves(tensors)
    if norm_type == 2.0:
        import optax

        return optax.global_norm(leaves)
    stacked = jnp.concatenate([jnp.abs(l.ravel()) for l in leaves])
    if norm_type == float("inf"):
        return stacked.max()
    return (stacked ** norm_type).sum() ** (1.0 / norm_type)


def get_global_norm(norm_list: Sequence[float]):
    """Reference ``get_global_norm``: combine pre-computed L2 norms."""
    total = 0.0
    for n in norm_list:
        total += float(n) ** 2.0
    return total ** 0.5


def get_grad_norm(grads, norm_type: float = 2.0, mpu=None):
    return get_global_norm_of_tensors(grads, norm_type, mpu)


def get_weight_norm(params, norm_type: float = 2.0, mpu=None):
    return get_global_norm_of_tensors(params, norm_type, mpu)


def clip_tensors_by_global_norm(tensors, max_norm: float = 1.0,
                                global_norm=None, mpu=None,
                                eps: float = 1e-6):
    """Scale a tree so its global norm is at most ``max_norm`` (reference
    ``clip_tensors_by_global_norm``). Returns (new_tree, global_norm) —
    immutable arrays mean the caller rebinds instead of mutating; ``mpu``
    is signature parity only (global arrays make its reduction moot)."""
    del mpu
    if global_norm is None:
        global_norm = get_global_norm_of_tensors(tensors)
    scale = jnp.minimum(1.0, max_norm / (global_norm + eps))
    return (jax.tree_util.tree_map(lambda t: t * scale, tensors),
            global_norm)


def clip_grad_norm_(parameters, max_norm: float, norm_type: float = 2.0,
                    mpu=None):
    """Reference ``clip_grad_norm_``: returns (clipped_tree, total_norm).
    NOTE the JAX shift — arrays are immutable, so unlike torch this does
    NOT mutate in place; rebind the result."""
    norm = get_global_norm_of_tensors(parameters, norm_type, mpu)
    clipped, _ = clip_tensors_by_global_norm(parameters, max_norm, norm)
    return clipped, norm


def clip_gradients(gradients, max_norm: float = 1.0):
    """Reference ``clip_gradients``."""
    clipped, norm = clip_grad_norm_(gradients, max_norm)
    return clipped, norm
