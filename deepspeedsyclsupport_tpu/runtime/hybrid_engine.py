"""Hybrid engine — one model, training AND generation (RLHF).

Analog of ``DeepSpeedHybridEngine`` (``runtime/hybrid_engine.py:32``, 446 LoC).
The reference's problem: training weights live inside ZeRO-3 partitions while
fast generation needs them gathered into inference containers, so it swaps
tensors between two module families per phase (``_zero3_forward:363``,
LoRA fuse/unfuse ``:138-160``).

Here the problem dissolves: parameters are ONE pytree; the training step and
the decode loop are two jitted programs closed over the same arrays. "Switching
phase" is calling the other function — XLA all-gathers sharded weights inside
the decode program exactly where needed, which IS the reference's gather path,
done by the compiler per-step instead of by tensor surgery per-phase.

What remains engine work and is provided:
* a cached generate program (prefill + scan decode, from ``inference/engine``)
  rebuilt only when shapes change — the role of the reference's inference
  module cache;
* RLHF bookkeeping parity: ``eval()``/``train()`` mode flags,
  per-phase latency counters (``_generate_latency``/``_training_latency``
  upstream), and a ``generate_to_train`` hand-off that is a no-op by design.
"""
import time
from typing import Any, Dict, Optional

import jax

from .engine import Engine
from ..inference.config import DSTpuInferenceConfig
from ..utils.logging import log_dist


class HybridEngine(Engine):
    def __init__(self, *args, inference_config: Optional[Dict] = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        if self.module is None or not hasattr(self.module, "decode_step"):
            raise ValueError(
                "HybridEngine needs a generative model (models.CausalLM "
                "protocol: decode_step/init_kv_cache)")
        self._inf_cfg = DSTpuInferenceConfig.from_config(inference_config)
        self._inf_engine = None
        self._merge_fn = None  # jitted LoRA fuse (built on first generate)
        self._training = True
        self.generate_time = 0.0
        self.train_time = 0.0

    # ------------------------------------------------------------ mode parity
    def eval(self):
        """Reference nn.Module-style phase flip (RLHF loops call these)."""
        self._training = False
        return self

    def train(self, mode: bool = True):
        self._training = mode
        return self

    # --------------------------------------------------------------- generate
    def generate(self, input_ids, **kwargs):
        """Sample from the CURRENT training weights (reference
        ``hybrid_engine.generate:174``). No weight copy: the decode program
        reads ``self.params`` directly, so every optimizer step is immediately
        reflected."""
        from ..inference.engine import InferenceEngine

        t0 = time.perf_counter()
        if self._inf_engine is None:
            # share topology; skip re-placement (params already on mesh)
            eng = InferenceEngine.__new__(InferenceEngine)
            eng.module = self.module
            eng.config = self._inf_cfg
            eng.topology = self.topology
            eng.params = None  # set per-call below
            eng._forward_fn = None
            eng._generate_fns = {}
            eng._rng = jax.random.PRNGKey(self._inf_cfg.seed)
            self._inf_engine = eng
        # live training params, cast to the training compute dtype (the same
        # cast the train step applies — generation sees exactly the weights
        # training uses, the invariant RLHF needs)
        from .lora import LoRAModel

        if isinstance(self.module, LoRAModel):
            # LoRA fuse (reference _fuse_lora, hybrid_engine.py:138): merge
            # adapters into the base ONCE per generate call, so the decode
            # loop runs on plain fused weights instead of recomputing
            # base + scale·A·B every step; nothing to unfuse (pure merge)
            if self._merge_fn is None:
                # base passed as an ARGUMENT: jitting self.module.merge
                # would bake the whole frozen tree into the executable
                self._merge_fn = jax.jit(self.module.merge_with)
            self._inf_engine.module = self.module.model
            # cast the ADAPTERS before merging — exactly what the train step
            # does (_loss_and_metrics casts params, then LoRAModel.loss
            # merges into the uncast base), so generation reads the same
            # merged weights training computes with — the RLHF
            # importance-ratio invariant
            self._inf_engine.params = self._merge_fn(
                self.module.base_params, self._cast_params(self.params))
        else:
            self._inf_engine.params = self._cast_params(self.params)
        out = self._inf_engine.generate(input_ids, **kwargs)
        self.generate_time = time.perf_counter() - t0
        return out

    # ------------------------------------------------------------------ train
    def train_batch(self, batch) -> Dict[str, Any]:
        t0 = time.perf_counter()
        metrics = super().train_batch(batch)
        self.train_time = time.perf_counter() - t0
        return metrics

    def latency_breakdown(self):
        """Reference RLHF telemetry (``hybrid_engine`` latency accessors)."""
        log_dist(f"hybrid: last generate {self.generate_time:.3f}s, "
                 f"last train_batch {self.train_time:.3f}s")
        return {"generate": self.generate_time, "train": self.train_time}
