"""JSON config system.

Analog of ``deepspeed/runtime/config.py`` (``DeepSpeedConfig``) +
``runtime/config_utils.py`` + the per-subsystem pydantic models
(``runtime/zero/config.py``, ``monitor/config.py``, ``comm/config.py`` …).

Same surface: one JSON file or dict drives the whole engine; the batch invariant
``train_batch_size = micro_batch_per_device × gradient_accumulation_steps ×
dp_world_size`` is enforced/derived exactly like the reference's
``_batch_assertion``/``_set_batch_related_parameters`` logic. Implementation is plain
dataclasses — no pydantic dependency — because the schema is small and static.
"""
import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from . import constants as C
from .offload_pipeline import DEFAULT_BUCKET_BYTES
from ..utils.logging import logger

AUTO = "auto"


def _sub(d: Dict[str, Any], key: str) -> Dict[str, Any]:
    v = d.get(key, {})
    if v in (None, False):
        return {}
    if v is True:
        return {"enabled": True}
    if not isinstance(v, dict):
        raise ValueError(f"config section {key!r} must be a dict, got {type(v)}")
    return v


@dataclass
class OptimizerConfig:
    """``optimizer`` section (reference: ``_configure_basic_optimizer``,
    ``engine.py:1267`` — Adam/AdamW/Lamb/OneBitAdam/Lion via op builders; ours map
    to optax transforms, fused by XLA)."""
    type: str = C.OPTIMIZER_TYPE_DEFAULT
    params: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "OptimizerConfig":
        return cls(type=str(d.get("type", C.OPTIMIZER_TYPE_DEFAULT)).lower(),
                   params=dict(d.get("params", {})))

    @property
    def lr(self) -> float:
        return float(self.params.get("lr", 1e-3))


@dataclass
class SchedulerConfig:
    """``scheduler`` section (reference: ``runtime/lr_schedules.py``)."""
    type: Optional[str] = None
    params: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SchedulerConfig":
        return cls(type=d.get("type"), params=dict(d.get("params", {})))


@dataclass
class Fp16Config:
    """``fp16`` section incl. dynamic loss scaling knobs
    (reference: ``runtime/fp16/loss_scaler.py`` DynamicLossScaler)."""
    enabled: bool = False
    loss_scale: float = 0.0  # 0 → dynamic
    initial_scale_power: int = C.INITIAL_LOSS_SCALE_POWER_DEFAULT
    loss_scale_window: int = C.LOSS_SCALE_WINDOW_DEFAULT
    hysteresis: int = C.HYSTERESIS_DEFAULT
    min_loss_scale: float = C.MIN_LOSS_SCALE_DEFAULT

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Fp16Config":
        return cls(enabled=bool(d.get("enabled", False)),
                   loss_scale=float(d.get("loss_scale", 0.0)),
                   initial_scale_power=int(d.get(C.INITIAL_LOSS_SCALE_POWER,
                                                 C.INITIAL_LOSS_SCALE_POWER_DEFAULT)),
                   loss_scale_window=int(d.get(C.LOSS_SCALE_WINDOW,
                                               C.LOSS_SCALE_WINDOW_DEFAULT)),
                   hysteresis=int(d.get(C.HYSTERESIS, C.HYSTERESIS_DEFAULT)),
                   min_loss_scale=float(d.get(C.MIN_LOSS_SCALE,
                                              C.MIN_LOSS_SCALE_DEFAULT)))

    @property
    def dynamic(self) -> bool:
        return self.loss_scale == 0.0

    @property
    def initial_scale(self) -> float:
        return float(self.loss_scale) if self.loss_scale else 2.0 ** self.initial_scale_power


@dataclass
class Bf16Config:
    enabled: bool = False

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Bf16Config":
        return cls(enabled=bool(d.get("enabled", False)))


@dataclass
class OffloadConfig:
    """``zero_optimization.offload_{optimizer,param}`` (reference:
    ``runtime/zero/offload_config.py``). ``device`` 'cpu' = host RAM via
    jax.device_put to the host backend; 'nvme' = async file swap (csrc/aio analog).

    Pipeline knobs (``runtime/offload_pipeline.py`` — see docs/offload.md):
    ``pipeline`` routes Adam-family offload through the bucketed D2H /
    host-Adam / H2D pipeline (reference ``offload_config.py`` carries the
    same flag name for its overlapped swap path); ``bucket_size`` is the
    size-targeted transfer/compute unit in bytes (small leaves coalesce);
    ``buffer_count`` is the NVMe moment-window depth in buckets (the
    reference's aio buffer_count — host RAM for moments is bounded by this
    window, not the store); ``overlap`` off runs identical math inline
    (the bit-parity debug arm)."""
    device: str = "none"  # none | cpu | nvme
    nvme_path: Optional[str] = None
    pin_memory: bool = True
    pipeline: bool = True
    bucket_size: int = DEFAULT_BUCKET_BYTES
    buffer_count: int = 2
    overlap: bool = True

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "OffloadConfig":
        bucket = int(d.get("bucket_size", DEFAULT_BUCKET_BYTES))
        buffers = int(d.get("buffer_count", 2))
        if bucket <= 0:
            raise ValueError(
                f"offload bucket_size must be > 0 bytes, got {bucket}")
        if buffers < 1:
            raise ValueError(
                f"offload buffer_count must be >= 1, got {buffers}")
        return cls(device=str(d.get("device", "none")),
                   nvme_path=d.get("nvme_path"),
                   pin_memory=bool(d.get("pin_memory", True)),
                   pipeline=bool(d.get("pipeline", True)),
                   bucket_size=bucket,
                   buffer_count=buffers,
                   overlap=bool(d.get("overlap", True)))

    @property
    def enabled(self) -> bool:
        return self.device not in ("none", None)


@dataclass
class ZeroConfig:
    """``zero_optimization`` section (reference: ``runtime/zero/config.py``
    ``DeepSpeedZeroConfig``). Stages keep reference semantics:

    0 → pure DP (replicated params/opt, psum grads)         [engine.py:1903]
    1 → optimizer state sharded over fsdp axis              [stage_1_and_2.py]
    2 → + gradient shards (reduce_scatter at boundary)      [stage_1_and_2.py:1004]
    3 → + parameter shards (XLA all-gathers per use)        [stage3.py]

    ZeRO++ knobs map to quantized-collective / hierarchical-partition analogs.
    """
    stage: int = C.ZERO_STAGE_DEFAULT
    offload_optimizer: OffloadConfig = field(default_factory=OffloadConfig)
    offload_param: OffloadConfig = field(default_factory=OffloadConfig)
    zero_quantized_weights: bool = False    # qwZ: int8 weight all-gather
    zero_quantized_gradients: bool = False  # qgZ: int8 grad reduce
    zero_hpz_partition_size: int = 1        # hpZ: secondary shard group size
    mics_shard_size: int = -1               # MiCS: sub-world shard groups
    overlap_comm: bool = True
    contiguous_gradients: bool = True
    reduce_bucket_size: int = 5 * 10**8

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ZeroConfig":
        stage = int(d.get(C.ZERO_STAGE, C.ZERO_STAGE_DEFAULT))
        if stage not in (0, 1, 2, 3):
            raise ValueError(f"zero_optimization.stage must be 0-3, got {stage}")
        return cls(
            stage=stage,
            offload_optimizer=OffloadConfig.from_dict(_sub(d, C.OFFLOAD_OPTIMIZER)),
            offload_param=OffloadConfig.from_dict(_sub(d, C.OFFLOAD_PARAM)),
            zero_quantized_weights=bool(d.get("zero_quantized_weights", False)),
            zero_quantized_gradients=bool(d.get("zero_quantized_gradients", False)),
            zero_hpz_partition_size=int(d.get("zero_hpz_partition_size", 1)),
            mics_shard_size=int(d.get("mics_shard_size", -1)),
            overlap_comm=bool(d.get("overlap_comm", True)),
            contiguous_gradients=bool(d.get("contiguous_gradients", True)),
            reduce_bucket_size=int(d.get("reduce_bucket_size", 5 * 10**8)),
        )


@dataclass
class ParallelismConfig:
    """Mesh axis sizes. dstpu-native section; also populated from reference-style
    sections (``tensor_parallel.tp_size``, ``pipeline.stages``,
    ``sequence_parallel_size``, ``moe.expert_parallel_size``) for config parity."""
    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    # pipeline microbatches per forward (pipeline.micro_batches; None =>
    # one per stage) — reference PipelineEngine streams GAS microbatches
    pp_microbatches: Optional[int] = None

    @classmethod
    def from_config_dict(cls, d: Dict[str, Any], zero_stage: int,
                         mics_shard_size: int = -1) -> "ParallelismConfig":
        p = _sub(d, C.PARALLELISM)
        tp = int(p.get("tp", _sub(d, C.TENSOR_PARALLEL).get("tp_size", 1)))
        pipe_sec = _sub(d, C.PIPELINE)
        pp = int(p.get("pp", pipe_sec.get("stages", 1)))
        pp_micro = pipe_sec.get("micro_batches")
        pp_micro = int(pp_micro) if pp_micro is not None else None
        ep = int(p.get("ep", _sub(d, C.MOE).get("expert_parallel_size", 1)))
        sp = int(p.get("sp", d.get(C.SEQUENCE_PARALLEL_SIZE, 1)))
        fsdp = int(p.get("fsdp", 0)) or 0
        dp = int(p.get("dp", 0)) or 0
        if mics_shard_size and mics_shard_size > 0:
            # MiCS (reference runtime/zero/mics.py MiCS_Init): ZeRO shard
            # groups smaller than the world — partition within an fsdp axis
            # of exactly the shard-group size, replicate across the data
            # axis. The reference's hierarchical allgather falls out of the
            # axis order (fsdp is ICI-inner; data crosses the slower tier).
            if fsdp and fsdp != mics_shard_size:
                raise ValueError(
                    f"mics_shard_size {mics_shard_size} conflicts with "
                    f"explicit fsdp={fsdp}")
            fsdp, dp = mics_shard_size, (dp or -1)
        elif not fsdp and not dp:
            # ZeRO>=1 shards over fsdp: default puts all data-parallel replicas on
            # the fsdp axis; plain DP keeps them on data.
            if zero_stage >= 1:
                fsdp, dp = -1, 1
            else:
                dp, fsdp = -1, 1
        elif not fsdp:
            fsdp = 1
        elif not dp:
            dp = 1
        return cls(dp=dp, fsdp=fsdp, tp=tp, pp=pp, ep=ep, sp=sp,
                   pp_microbatches=pp_micro)


@dataclass
class ActivationCheckpointingConfig:
    """``activation_checkpointing`` (reference:
    ``runtime/activation_checkpointing/checkpointing.py``). Under XLA this maps to
    ``jax.checkpoint`` policies rather than manual save/recompute."""
    # section presence turns checkpointing ON unless explicitly disabled
    # ("enabled" is a dstpu extension: the reference has no off-switch in
    # the section, and partition_activations means TP-partitioning there,
    # NOT enablement — ported configs with partition_activations=false
    # still expect remat on)
    enabled: bool = True
    partition_activations: bool = False
    number_checkpoints: Optional[int] = None
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    policy: str = "nothing_saveable"  # jax.checkpoint policy name

    # zero-arg jax.checkpoint_policies only — factory-style names (e.g.
    # save_only_these_names) would be silently misused as policies
    VALID_POLICIES = ("nothing_saveable", "everything_saveable",
                      "dots_saveable", "checkpoint_dots",
                      "offload_dots_to_host",
                      "dots_with_no_batch_dims_saveable",
                      "checkpoint_dots_with_no_batch_dims")

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ActivationCheckpointingConfig":
        policy = str(d.get("policy", "nothing_saveable"))
        if policy not in cls.VALID_POLICIES:
            raise ValueError(
                f"activation_checkpointing.policy {policy!r} is not a "
                f"supported jax.checkpoint policy; choose one of "
                f"{cls.VALID_POLICIES}")
        return cls(enabled=bool(d.get("enabled", True)),
                   partition_activations=bool(d.get("partition_activations", False)),
                   number_checkpoints=d.get("number_checkpoints"),
                   contiguous_memory_optimization=bool(
                       d.get("contiguous_memory_optimization", False)),
                   cpu_checkpointing=bool(d.get("cpu_checkpointing", False)),
                   policy=policy)


@dataclass
class MonitorConfig:
    """``tensorboard``/``wandb``/``csv_monitor``/``jsonl_monitor`` sections
    (reference: ``monitor/config.py``; jsonl is the rank-local flight-recorder
    sink, see ``monitor/telemetry.py``)."""
    tensorboard_enabled: bool = False
    tensorboard_output_path: str = ""
    tensorboard_job_name: str = "DSTpuJobName"
    wandb_enabled: bool = False
    wandb_project: Optional[str] = None
    wandb_team: Optional[str] = None
    wandb_group: Optional[str] = None
    csv_enabled: bool = False
    csv_output_path: str = ""
    csv_job_name: str = "DSTpuJobName"
    csv_flush_interval: int = 10  # write batches between csv flushes
    jsonl_enabled: bool = False
    jsonl_output_path: str = ""
    jsonl_job_name: str = "DSTpuJobName"
    jsonl_flush_interval: int = 64  # records buffered between jsonl flushes

    @classmethod
    def from_config_dict(cls, d: Dict[str, Any]) -> "MonitorConfig":
        tb = _sub(d, C.MONITOR_TENSORBOARD)
        wb = _sub(d, C.MONITOR_WANDB)
        csv = _sub(d, C.MONITOR_CSV)
        jl = _sub(d, C.MONITOR_JSONL)
        return cls(
            tensorboard_enabled=bool(tb.get("enabled", False)),
            tensorboard_output_path=tb.get("output_path", ""),
            tensorboard_job_name=tb.get("job_name", "DSTpuJobName"),
            wandb_enabled=bool(wb.get("enabled", False)),
            wandb_project=wb.get("project"),
            wandb_team=wb.get("team"),
            wandb_group=wb.get("group"),
            csv_enabled=bool(csv.get("enabled", False)),
            csv_output_path=csv.get("output_path", ""),
            csv_job_name=csv.get("job_name", "DSTpuJobName"),
            csv_flush_interval=int(csv.get("flush_interval", 10)),
            jsonl_enabled=bool(jl.get("enabled", False)),
            jsonl_output_path=jl.get("output_path", ""),
            jsonl_job_name=jl.get("job_name", "DSTpuJobName"),
            jsonl_flush_interval=int(jl.get("flush_interval", 64)),
        )

    @property
    def enabled(self) -> bool:
        return (self.tensorboard_enabled or self.wandb_enabled
                or self.csv_enabled or self.jsonl_enabled)


@dataclass
class TelemetryConfig:
    """``telemetry`` section — the structured observability layer
    (``monitor/telemetry.py``): flight recorder + rank-local JSONL, goodput
    accounting, recompile detection, HBM gauges, heartbeat file and
    on-demand ``jax.profiler`` trace windows. ``DSTPU_TELEMETRY=1`` forces
    ``enabled`` at runtime without a config edit."""
    enabled: bool = False
    output_dir: str = "telemetry_logs"
    ring_size: int = 4096
    flush_interval_records: int = 64
    memory_interval_steps: int = 10
    heartbeat_enabled: bool = True
    heartbeat_interval_s: float = 1.0
    stack_dump_on_hang: bool = True
    goodput_enabled: bool = True
    # block on the step's outputs before timing it: device-accurate step
    # spans, at the cost of the host/device dispatch overlap
    sync_timing: bool = False
    # Prometheus textfile-collector snapshot (metrics_rank<N>.prom,
    # atomic rename) refreshed at heartbeat cadence — long multi-host runs
    # are scraped off this file instead of anyone tailing JSONL
    textfile_enabled: bool = False
    textfile_interval_s: float = 15.0
    # Collective hang watchdog (comm/watchdog.py): the engine arms a
    # deadline around each step's collective dispatch; on expiry the
    # watchdog thread dumps stacks, flushes the recorder and exits rc 218
    # (the comm-hang contract the elastic agent restarts distinctly).
    # warmup_deadline_s covers the first (compiling) step; None = 10x.
    watchdog_enabled: bool = False
    watchdog_deadline_s: float = 60.0
    watchdog_warmup_deadline_s: Optional[float] = None
    watchdog_poll_s: float = 0.25
    trace_start_step: Optional[int] = None
    trace_num_steps: int = 3
    trace_dir: Optional[str] = None
    # MFU ledger (monitor/mfu.py + analysis/roofline.py): auto-capture ONE
    # jax.profiler window around a clean (non-compiling) step — earliest at
    # mfu_step — and join it against the roofline partition via
    # Engine.mfu_ledger(). The window costs one synced step; everything
    # else is offline.
    mfu_enabled: bool = False
    mfu_step: int = 3

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TelemetryConfig":
        hb = dict(d.get("heartbeat", {}))
        tr = dict(d.get("trace", {}))
        tf = dict(d.get("textfile", {}))
        wd = dict(d.get("watchdog", {}))
        mfu = dict(d.get("mfu", {}))
        mfu_step = int(mfu.get("step", 3))
        if mfu_step < 1:
            raise ValueError(f"telemetry.mfu.step must be >= 1, got "
                             f"{mfu_step} (step 1 includes the first "
                             f"compile; the capture skips compiling steps "
                             f"anyway)")
        ring = int(d.get("ring_size", 4096))
        if ring <= 0:
            raise ValueError(f"telemetry.ring_size must be > 0, got {ring}")
        tf_interval = float(tf.get("interval_s", 15.0))
        if tf_interval <= 0:
            raise ValueError(f"telemetry.textfile.interval_s must be > 0, "
                             f"got {tf_interval}")
        wd_deadline = float(wd.get("deadline_s", 60.0))
        wd_poll = float(wd.get("poll_s", 0.25))
        if wd_deadline <= 0 or wd_poll <= 0:
            raise ValueError(
                f"telemetry.watchdog deadline_s/poll_s must be > 0, got "
                f"{wd_deadline}/{wd_poll}")
        wd_warmup = wd.get("warmup_deadline_s")
        if wd_warmup is not None and float(wd_warmup) < wd_deadline:
            raise ValueError(
                f"telemetry.watchdog.warmup_deadline_s ({wd_warmup}) must "
                f"cover at least deadline_s ({wd_deadline}) — the first "
                f"armed step includes compilation")
        start = tr.get("start_step")
        return cls(
            enabled=bool(d.get("enabled", False)),
            output_dir=str(d.get("output_dir", "telemetry_logs")),
            ring_size=ring,
            flush_interval_records=int(d.get("flush_interval_records", 64)),
            memory_interval_steps=int(d.get("memory_interval_steps", 10)),
            heartbeat_enabled=bool(hb.get("enabled", True)),
            heartbeat_interval_s=float(hb.get("interval_s", 1.0)),
            stack_dump_on_hang=bool(d.get("stack_dump_on_hang", True)),
            sync_timing=bool(d.get("sync_timing", False)),
            textfile_enabled=bool(tf.get("enabled", False)),
            textfile_interval_s=tf_interval,
            watchdog_enabled=bool(wd.get("enabled", False)),
            watchdog_deadline_s=wd_deadline,
            watchdog_warmup_deadline_s=(None if wd_warmup is None
                                        else float(wd_warmup)),
            watchdog_poll_s=wd_poll,
            goodput_enabled=bool(d.get("goodput", {}).get("enabled", True)
                                 if isinstance(d.get("goodput"), dict)
                                 else d.get("goodput", True)),
            trace_start_step=None if start is None else int(start),
            trace_num_steps=int(tr.get("num_steps", 3)),
            trace_dir=tr.get("trace_dir"),
            mfu_enabled=bool(mfu.get("enabled", False)),
            mfu_step=mfu_step,
        )


@dataclass
class SentinelConfig:
    """``sentinel`` section — the training-health sentinel
    (``runtime/sentinel.py``): in-graph NaN/spike gating piggybacked on the
    step's output fetch, host-side robust z-score detection over the
    loss/grad-norm history, and the graduated response ladder
    ``warn → skip_batch → rollback → abort`` (rc 220)."""
    enabled: bool = False
    # spike detection arms only after this many healthy steps of history —
    # early-training loss moves fast and would trip any static threshold
    warmup_steps: int = 20
    # history window for the robust (median/MAD) statistics
    window: int = 64
    # EWMA smoothing factor for the drift-following baseline
    ewma_alpha: float = 0.1
    # robust z at which an observation is a WARN (journaled, update applied)
    z_warn: float = 4.0
    # robust z at which the in-graph gate discards the update (skip_batch)
    z_skip: float = 8.0
    # consecutive anomalous steps before the ladder escalates to rollback
    skip_limit: int = 3
    # rollbacks without an intervening healthy window before abort (rc 220)
    rollback_limit: int = 2
    # healthy steps that must be observed BEYOND a saved tag before the
    # sentinel promotes it as a last-good rollback target
    last_good_k: int = 4
    # transient LR cut after a rollback: gradients are scaled by lr_cut for
    # lr_cut_steps steps (1.0 / 0 disables)
    lr_cut: float = 1.0
    lr_cut_steps: int = 0
    # decision lag in steps: verdict for step N is issued at the boundary of
    # step N+lag, when N's scalars have already materialized — the sentinel
    # never adds a blocking host sync to the step path
    lag: int = 1
    # rollback source; defaults to wherever the engine last saved
    checkpoint_dir: Optional[str] = None
    # health_journal_rank<N>.jsonl location; defaults to telemetry.output_dir
    journal_dir: Optional[str] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SentinelConfig":
        z_warn = float(d.get("z_warn", 4.0))
        z_skip = float(d.get("z_skip", 8.0))
        if z_skip < z_warn:
            raise ValueError(f"sentinel.z_skip ({z_skip}) must be >= z_warn "
                             f"({z_warn}) — the ladder escalates, it does "
                             f"not invert")
        lag = int(d.get("lag", 1))
        if lag < 1:
            raise ValueError(f"sentinel.lag must be >= 1, got {lag} — lag 0 "
                             f"would block the host on the in-flight step")
        for key, lo in (("warmup_steps", 1), ("window", 4),
                        ("skip_limit", 1), ("rollback_limit", 0),
                        ("last_good_k", 1), ("lr_cut_steps", 0)):
            if int(d.get(key, lo)) < lo:
                raise ValueError(f"sentinel.{key} must be >= {lo}, got "
                                 f"{d.get(key)}")
        return cls(
            enabled=bool(d.get("enabled", False)),
            warmup_steps=int(d.get("warmup_steps", 20)),
            window=int(d.get("window", 64)),
            ewma_alpha=float(d.get("ewma_alpha", 0.1)),
            z_warn=z_warn,
            z_skip=z_skip,
            skip_limit=int(d.get("skip_limit", 3)),
            rollback_limit=int(d.get("rollback_limit", 2)),
            last_good_k=int(d.get("last_good_k", 4)),
            lr_cut=float(d.get("lr_cut", 1.0)),
            lr_cut_steps=int(d.get("lr_cut_steps", 0)),
            lag=lag,
            checkpoint_dir=d.get("checkpoint_dir"),
            journal_dir=d.get("journal_dir"),
        )


@dataclass
class CommsLoggerConfig:
    """``comms_logger`` section (reference: ``comm/config.py``)."""
    enabled: bool = False
    verbose: bool = False
    debug: bool = False

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "CommsLoggerConfig":
        return cls(enabled=bool(d.get("enabled", False)),
                   verbose=bool(d.get("verbose", False)),
                   debug=bool(d.get("debug", False)))


@dataclass
class FlopsProfilerConfig:
    """``flops_profiler`` section (reference: ``profiling/config.py``)."""
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FlopsProfilerConfig":
        return cls(enabled=bool(d.get("enabled", False)),
                   profile_step=int(d.get("profile_step", 1)),
                   module_depth=int(d.get("module_depth", -1)),
                   top_modules=int(d.get("top_modules", 1)),
                   detailed=bool(d.get("detailed", True)),
                   output_file=d.get("output_file"))


@dataclass
class CheckpointConfig:
    """``checkpoint`` section (reference: ``runtime/config.py`` checkpoint_config +
    tag validation collective ``engine.py:3033``)."""
    tag_validation: str = "Warn"  # Ignore | Warn | Fail
    use_node_local_storage: bool = False
    load_universal: bool = False
    # sync by default (reference: TorchCheckpointEngine); the async
    # Nebula-analog engine is opt-in via async_save or engine="async"
    async_save: bool = False
    engine: str = "native"  # native | async (checkpoint/ckpt_engine.py)
    # rotation: keep the newest N *verified* checkpoints, GC older ones after
    # each durable save (checkpoint/engine.py::rotate_checkpoints). 0 = never
    # delete anything (the default — rotation is opt-in).
    keep_last_n: int = 0

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "CheckpointConfig":
        tv = str(d.get("tag_validation", "Warn")).capitalize()
        if tv not in ("Ignore", "Warn", "Fail"):
            raise ValueError(f"checkpoint.tag_validation must be Ignore|Warn|Fail, got {tv}")
        async_save = bool(d.get("async_save", False))
        engine = str(d.get("engine", "async" if async_save else "native"))
        if engine not in ("native", "async"):
            raise ValueError(f"checkpoint.engine must be native|async, got {engine!r}")
        if "engine" in d and "async_save" in d and \
                async_save != (engine == "async"):
            raise ValueError(
                f"contradictory checkpoint config: engine={engine!r} with "
                f"async_save={async_save}")
        async_save = engine == "async"  # keep the two views consistent
        keep_last_n = int(d.get("keep_last_n", 0))
        if keep_last_n < 0:
            raise ValueError(
                f"checkpoint.keep_last_n must be >= 0, got {keep_last_n}")
        return cls(tag_validation=tv,
                   use_node_local_storage=bool(d.get("use_node_local_storage", False)),
                   load_universal=bool(d.get("load_universal", False)),
                   async_save=async_save, engine=engine,
                   keep_last_n=keep_last_n)


@dataclass
class ProgressiveLayerDropConfig:
    """``progressive_layer_drop`` section (reference:
    ``runtime/progressive_layer_drop.py``, constants PLD_*)."""
    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ProgressiveLayerDropConfig":
        return cls(enabled=bool(d.get("enabled", False)),
                   theta=float(d.get("theta", 0.5)),
                   gamma=float(d.get("gamma", 0.001)))


@dataclass
class DataEfficiencyConfig:
    """``data_efficiency`` section (reference:
    ``runtime/data_pipeline/config.py`` + ``constants.py`` key families),
    plus the legacy top-level ``curriculum_learning`` section. Resolved
    curriculum/random-ltd dicts feed ``runtime/data_pipeline``."""
    enabled: bool = False
    seed: int = 1234
    curriculum: Optional[Dict[str, Any]] = None      # scheduler config dict
    curriculum_metric: str = "seqlen"
    random_ltd: Optional[Dict[str, Any]] = None      # scheduler config dict

    @classmethod
    def from_config_dict(cls, d: Dict[str, Any]) -> "DataEfficiencyConfig":
        de = dict(d.get("data_efficiency", {}))
        sampling = dict(de.get("data_sampling", {}))
        routing = dict(de.get("data_routing", {}))
        curriculum = None
        metric = "seqlen"
        # nested (data_efficiency.data_sampling.curriculum_learning) …
        cl = dict(sampling.get("curriculum_learning", {}))
        if cl.get("enabled", False):
            metrics = dict(cl.get("curriculum_metrics", {}))
            if len(metrics) > 1:
                raise ValueError(
                    "multiple curriculum_metrics are not supported; "
                    f"configure exactly one (got {sorted(metrics)})")
            if metrics:  # reference: named metric sub-sections
                metric, cl = next(iter(metrics.items()))
                cl = dict(cl)
            curriculum = cl
        # … or legacy top-level curriculum_learning
        legacy = dict(d.get("curriculum_learning", {}))
        if curriculum is None and legacy.get("enabled", False):
            curriculum = legacy
            metric = legacy.get("curriculum_type", "seqlen")
        ltd = dict(routing.get("random_ltd", {}))
        random_ltd = ltd if ltd.get("enabled", False) else None
        enabled = bool(de.get("enabled", False) or curriculum is not None
                       or random_ltd is not None)
        return cls(enabled=enabled, seed=int(de.get("seed", 1234)),
                   curriculum=curriculum, curriculum_metric=metric,
                   random_ltd=random_ltd)


@dataclass
class DSTpuConfig:
    """Top-level typed config (reference: ``DeepSpeedConfig``)."""

    raw: Dict[str, Any]
    train_batch_size: int
    train_micro_batch_size_per_gpu: int
    gradient_accumulation_steps: int
    optimizer: OptimizerConfig
    scheduler: SchedulerConfig
    fp16: Fp16Config
    bf16: Bf16Config
    zero: ZeroConfig
    parallelism: ParallelismConfig
    activation_checkpointing: ActivationCheckpointingConfig
    monitor: MonitorConfig
    comms_logger: CommsLoggerConfig
    flops_profiler: FlopsProfilerConfig
    checkpoint: CheckpointConfig
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    sentinel: SentinelConfig = field(default_factory=SentinelConfig)
    progressive_layer_drop: ProgressiveLayerDropConfig = field(
        default_factory=ProgressiveLayerDropConfig)
    data_efficiency: DataEfficiencyConfig = field(
        default_factory=DataEfficiencyConfig)
    gradient_clipping: float = C.GRADIENT_CLIPPING_DEFAULT
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    steps_per_print: int = C.STEPS_PER_PRINT_DEFAULT
    wall_clock_breakdown: bool = False
    seed: int = C.SEED_DEFAULT
    dump_state: bool = False

    # ------------------------------------------------------------------ parse
    @classmethod
    def from_config(cls, config, dp_world_size: Optional[int] = None) -> "DSTpuConfig":
        if isinstance(config, (str, os.PathLike)):
            with open(config) as f:
                d = json.load(f)
        elif isinstance(config, dict):
            d = dict(config)
        elif isinstance(config, DSTpuConfig):
            return config
        else:
            raise TypeError(f"config must be dict or path, got {type(config)}")

        for key in set(d) & C.IGNORED_REFERENCE_KEYS:
            logger.warning("config key %r has no TPU analog; ignored", key)

        fp16 = Fp16Config.from_dict(_sub(d, C.FP16))
        bf16 = Bf16Config.from_dict(_sub(d, C.BF16))
        if fp16.enabled and bf16.enabled:
            raise ValueError("fp16 and bf16 cannot both be enabled")
        zero = ZeroConfig.from_dict(_sub(d, C.ZERO_OPTIMIZATION))

        cfg = cls(
            raw=d,
            train_batch_size=0,
            train_micro_batch_size_per_gpu=0,
            gradient_accumulation_steps=0,
            optimizer=OptimizerConfig.from_dict(_sub(d, C.OPTIMIZER)),
            scheduler=SchedulerConfig.from_dict(_sub(d, C.SCHEDULER)),
            fp16=fp16,
            bf16=bf16,
            zero=zero,
            parallelism=ParallelismConfig.from_config_dict(
                d, zero.stage, zero.mics_shard_size),
            activation_checkpointing=ActivationCheckpointingConfig.from_dict(
                _sub(d, C.ACTIVATION_CHECKPOINTING)),
            monitor=MonitorConfig.from_config_dict(d),
            comms_logger=CommsLoggerConfig.from_dict(_sub(d, C.COMMS_LOGGER)),
            flops_profiler=FlopsProfilerConfig.from_dict(_sub(d, C.FLOPS_PROFILER)),
            checkpoint=CheckpointConfig.from_dict(_sub(d, C.CHECKPOINT)),
            telemetry=TelemetryConfig.from_dict(_sub(d, C.TELEMETRY)),
            sentinel=SentinelConfig.from_dict(_sub(d, "sentinel")),
            progressive_layer_drop=ProgressiveLayerDropConfig.from_dict(
                _sub(d, "progressive_layer_drop")),
            data_efficiency=DataEfficiencyConfig.from_config_dict(d),
            gradient_clipping=float(d.get(C.GRADIENT_CLIPPING,
                                          C.GRADIENT_CLIPPING_DEFAULT)),
            prescale_gradients=bool(d.get(C.PRESCALE_GRADIENTS, False)),
            gradient_predivide_factor=float(d.get(C.GRADIENT_PREDIVIDE_FACTOR, 1.0)),
            steps_per_print=int(d.get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)),
            wall_clock_breakdown=bool(d.get(C.WALL_CLOCK_BREAKDOWN, False)),
            seed=int(d.get(C.SEED, C.SEED_DEFAULT)),
            dump_state=bool(d.get(C.DUMP_STATE, False)),
        )
        if dp_world_size is not None:
            cfg.resolve_batch_sizes(dp_world_size)
        return cfg

    # ---------------------------------------------------------- batch invariant
    def resolve_batch_sizes(self, dp_world_size: int) -> None:
        """Enforce/derive ``train_batch = micro_batch × grad_accum × dp_world``
        (reference: ``runtime/config.py`` ``_set_batch_related_parameters``)."""
        d = self.raw
        tb = d.get(C.TRAIN_BATCH_SIZE)
        mb = d.get(C.TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        gas = d.get(C.GRADIENT_ACCUMULATION_STEPS)
        tb = None if tb == AUTO else tb
        mb = None if mb == AUTO else mb
        gas = None if gas == AUTO else gas

        if tb is not None and mb is not None and gas is not None:
            if tb != mb * gas * dp_world_size:
                raise ValueError(
                    f"batch invariant violated: train_batch_size={tb} != "
                    f"micro({mb}) × grad_accum({gas}) × dp_world({dp_world_size})")
        elif tb is not None and mb is not None:
            if tb % (mb * dp_world_size) != 0:
                raise ValueError(
                    f"train_batch_size={tb} not divisible by micro({mb}) × "
                    f"dp_world({dp_world_size})")
            gas = tb // (mb * dp_world_size)
        elif tb is not None and gas is not None:
            if tb % (gas * dp_world_size) != 0:
                raise ValueError(
                    f"train_batch_size={tb} not divisible by grad_accum({gas}) × "
                    f"dp_world({dp_world_size})")
            mb = tb // (gas * dp_world_size)
        elif mb is not None:
            gas = gas or 1
            tb = mb * gas * dp_world_size
        elif tb is not None:
            mb = max(1, tb // dp_world_size)
            gas = tb // (mb * dp_world_size)
            if tb != mb * gas * dp_world_size:
                raise ValueError(
                    f"train_batch_size={tb} not divisible by dp_world({dp_world_size})")
        else:
            raise ValueError(
                "at least one of train_batch_size / train_micro_batch_size_per_gpu "
                "must be configured")
        self.train_batch_size = int(tb)
        self.train_micro_batch_size_per_gpu = int(mb)
        self.gradient_accumulation_steps = int(gas)

    # ------------------------------------------------------------------ helpers
    @property
    def compute_dtype(self):
        import jax.numpy as jnp

        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32

    def to_dict(self) -> Dict[str, Any]:
        out = dict(self.raw)
        out[C.TRAIN_BATCH_SIZE] = self.train_batch_size
        out[C.TRAIN_MICRO_BATCH_SIZE_PER_GPU] = self.train_micro_batch_size_per_gpu
        out[C.GRADIENT_ACCUMULATION_STEPS] = self.gradient_accumulation_steps
        return out
