"""LoRA adapters — low-rank fine-tuning with fuse-for-generate.

Analog of the reference hybrid engine's LoRA handling
(``runtime/hybrid_engine.py:138-160`` ``_fuse_lora``/``_unfuse_lora``: merge
``W += scale·B·A`` into the base weight before fast generation, subtract it
back before training) and of the PEFT-style adapters DeepSpeed-Chat trains.

Functional recast: the base pytree is FROZEN and closed over; the trainable
tree the engine sees is only the adapters, so "unfuse" never exists —
training differentiates through ``W_eff = W + scale·A·B`` recomputed inside
the jitted step, and "fuse" is a pure jitted merge producing the effective
weights once per generate phase (the reference's fuse, without the in-place
surgery or the possibility of forgetting to unfuse).
"""
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]

__all__ = ["LoRAConfig", "LoRAModel"]


@dataclass
class LoRAConfig:
    r: int = 8
    alpha: float = 16.0
    # leaf-path suffixes to adapt (default: attention projections, the
    # DeepSpeed-Chat / LoRA-paper default)
    target_patterns: Tuple[str, ...] = ("attn/wq", "attn/wk", "attn/wv",
                                        "attn/wo")
    init_std: float = 0.02

    @property
    def scale(self) -> float:
        return self.alpha / self.r


def _path_str(kp) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "name", k)))
                    for k in kp)


class LoRAModel:
    """Wrap a loss-protocol model: ``init_params`` returns ONLY the adapter
    tree; the engine trains it while the base stays frozen. ``merge`` builds
    the fused full-weight pytree for generation."""

    def __init__(self, model: Any, base_params: Params, config: LoRAConfig):
        self.model = model
        self.config = model.config  # engine/infra pass-through
        self.lora_config = config
        self.base_params = base_params
        self._targets = []
        for kp, leaf in jax.tree_util.tree_flatten_with_path(base_params)[0]:
            path = _path_str(kp)
            if any(path == t or path.endswith("/" + t)
                   for t in config.target_patterns):
                if jnp.ndim(leaf) not in (2, 3):
                    raise ValueError(f"LoRA target {path} has rank "
                                     f"{jnp.ndim(leaf)}; need 2-D (or "
                                     f"stacked [L, in, out]) matrices")
                self._targets.append(path)
        if not self._targets:
            raise ValueError(f"no leaves matched {config.target_patterns}")

    # ------------------------------------------------------------------ params
    def init_params(self, rng: Optional[jax.Array] = None) -> Params:
        cfg = self.lora_config
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        flat = jax.tree_util.tree_flatten_with_path(self.base_params)[0]
        out: Params = {}
        ks = iter(jax.random.split(rng, len(self._targets) + 1))
        for kp, leaf in flat:
            path = _path_str(kp)
            if path not in self._targets:
                continue
            shape = jnp.shape(leaf)
            # stacked scan layers carry a leading [L] dim
            lead, (n_in, n_out) = shape[:-2], shape[-2:]
            out[path] = {
                # A ~ N(0, σ), B = 0 → adapters start as an exact no-op
                "A": jax.random.normal(next(ks), lead + (n_in, cfg.r),
                                       jnp.float32) * cfg.init_std,
                "B": jnp.zeros(lead + (cfg.r, n_out), jnp.float32),
            }
        return out

    # ------------------------------------------------------------------- merge
    def merge_with(self, base_params: Params, lora_params: Params) -> Params:
        """Fused full weights: ``base + scale·A·B`` at every target (the
        reference ``_fuse_lora``; pure, so there is nothing to unfuse). Both
        trees are explicit arguments so callers can jit WITHOUT baking the
        base weights into the executable as constants."""
        scale = self.lora_config.scale

        def fuse(kp, leaf):
            path = _path_str(kp)
            ab = lora_params.get(path)
            if ab is None:
                return leaf
            delta = jnp.einsum("...ir,...ro->...io", ab["A"], ab["B"])
            return (leaf + scale * delta).astype(leaf.dtype)

        return jax.tree_util.tree_map_with_path(fuse, base_params)

    def merge(self, lora_params: Params) -> Params:
        return self.merge_with(self.base_params, lora_params)

    # ----------------------------------------------------- engine protocol
    def loss(self, lora_params: Params, batch: Dict[str, Any],
             rng: Optional[jax.Array] = None, train: bool = True):
        return self.model.loss(self.merge(lora_params), batch, rng=rng,
                               train=train)

    def apply(self, lora_params: Params, input_ids, **kw):
        return self.model.apply(self.merge(lora_params), input_ids, **kw)

    def sharding_rules(self, path, shape):
        return None  # adapters are tiny: replicate

    # generation protocol delegates through the merged weights
    def init_kv_cache(self, *a, **kw):
        return self.model.init_kv_cache(*a, **kw)

    def decode_step(self, lora_params: Params, cache, tokens, **kw):
        return self.model.decode_step(self.merge(lora_params), cache,
                                      tokens, **kw)

    def num_adapter_params(self) -> int:
        import numpy as np

        return sum(int(np.prod(np.shape(l))) for l in
                   jax.tree_util.tree_leaves(self.init_params()))
