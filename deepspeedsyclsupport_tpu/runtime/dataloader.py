"""Device-feeding data loader.

Analog of ``deepspeed/runtime/dataloader.py`` (``DeepSpeedDataLoader``, 162 LoC,
curriculum-capable). The TPU version's job: take any host iterable of numpy/array
pytrees and hand the engine batches already placed with the input sharding
(dim 0 split over (data, fsdp)), double-buffered so host→HBM transfer overlaps step
``n`` compute (the reference gets this from CUDA streams + pin_memory).

Iterator state is checkpointable: both loaders expose
``state_dict()/load_state_dict()`` (epoch / within-epoch offset / shuffle
seed), which the engine rides into checkpoint meta so a resume continues the
stream where the save left it instead of silently replaying or skipping data
— and which the training sentinel's rollback path (``runtime/sentinel.py``)
uses to rewind the stream to the last-good step deterministically.
:class:`CheckpointableDataLoader` goes further: an iterator-object loader
over a ``Sequence`` dataset whose ``load_state_dict`` takes effect on the
*next* ``__next__`` even mid-iteration — exactly what an in-place rollback
needs (a generator-style loader's live iterator could not be rewound).
"""
import itertools
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import jax
import numpy as np

from ..comm.topology import MeshTopology


class DSTpuDataLoader:
    def __init__(self, dataset: Iterable, topo: MeshTopology,
                 batch_fn: Optional[Callable[[Any], Any]] = None,
                 prefetch: int = 2, drop_last: bool = True):
        self.dataset = dataset
        self.topo = topo
        self.batch_fn = batch_fn
        self.prefetch = max(0, prefetch)
        self.drop_last = drop_last
        self._len = None
        self._epoch = 0    # completed passes over the dataset
        self._offset = 0   # batches yielded within the current epoch
        try:
            self._len = len(dataset)  # type: ignore[arg-type]
        except TypeError:
            pass

    def __len__(self):
        if self._len is None:
            raise TypeError("underlying dataset has no length")
        return self._len

    # ------------------------------------------------------------ state
    @property
    def position(self) -> int:
        """Total batches yielded across the loader's lifetime (epoch-major)
        when the dataset is sized; within-epoch offset otherwise."""
        if self._len is None:
            return self._offset
        return self._epoch * self._len + self._offset

    def state_dict(self) -> dict:
        return {"epoch": self._epoch, "offset": self._offset}

    def load_state_dict(self, sd: dict) -> None:
        """Restore stream position. Takes effect at the next ``__iter__``:
        the epoch's first ``offset`` batches are fast-forwarded (consumed
        from the underlying iterable, not yielded)."""
        self._epoch = int(sd.get("epoch", 0))
        self._offset = int(sd.get("offset", 0))

    def _place(self, batch):
        def put(x):
            arr = np.asarray(x)
            return jax.device_put(arr, self.topo.data_sharding(arr.ndim))

        return jax.tree_util.tree_map(put, batch)

    def __iter__(self) -> Iterator[Any]:
        it = iter(self.dataset)
        if self._offset:
            # resume-from-checkpoint fast-forward: burn the already-consumed
            # head of the epoch so the first yielded batch is the one the
            # saved run would have seen next
            it = itertools.islice(it, self._offset, None)
        if self.batch_fn is not None:
            it = (self.batch_fn(b) for b in it)

        def track(source):
            # increment BEFORE yield: while the consumer trains on batch k
            # the recorded offset is already k+1, so a checkpoint taken at
            # that step resumes on the NEXT batch, not a replay of k. (With
            # prefetch>0 the ring pulls ahead and the offset counts batches
            # handed to the ring — exact-position checkpointing wants
            # prefetch=0 or CheckpointableDataLoader.)
            for b in source:
                self._offset += 1
                yield b
            self._epoch += 1
            self._offset = 0

        placed = (self._place(b) for b in track(it))
        if self.prefetch == 0:
            yield from placed
            return
        # simple software pipeline: keep `prefetch` batches in flight; device_put is
        # async so transfers overlap the consumer's compute.
        buf = list(itertools.islice(placed, self.prefetch))
        for nxt in placed:
            yield buf.pop(0)
            buf.append(nxt)
        yield from buf


class CheckpointableDataLoader(DSTpuDataLoader):
    """Random-access loader over a ``Sequence`` dataset with deterministic
    per-epoch shuffling and *immediate-effect* rewind.

    Differences from the base generator loader, all in service of the
    sentinel's rollback contract:

    * iterator-object semantics: ``__iter__`` returns ``self`` and
      ``__next__`` derives the batch index from ``(epoch, offset)`` state on
      every call — ``load_state_dict`` mid-iteration rewinds the very next
      batch (no live generator holding a stale position).
    * per-epoch shuffle from ``np.random.default_rng((seed, epoch))``: the
      permutation is a pure function of (seed, epoch), so a rewound or
      resumed run re-derives the identical order with no RNG state blob.
    * no prefetch ring: rewind would have to invalidate in-flight batches.
    """

    def __init__(self, dataset: Sequence, topo: MeshTopology,
                 batch_fn: Optional[Callable[[Any], Any]] = None,
                 shuffle: bool = False, seed: int = 0):
        super().__init__(dataset, topo, batch_fn=batch_fn, prefetch=0)
        if self._len is None:
            raise TypeError("CheckpointableDataLoader needs a Sequence "
                            "dataset (random access + __len__)")
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        self._perm_epoch = None
        self._perm = None

    def state_dict(self) -> dict:
        return {"epoch": self._epoch, "offset": self._offset,
                "shuffle": self.shuffle, "seed": self.seed}

    def load_state_dict(self, sd: dict) -> None:
        super().load_state_dict(sd)
        if "seed" in sd:
            self.seed = int(sd["seed"])

    def _order(self, epoch: int) -> np.ndarray:
        if self._perm_epoch != epoch:
            if self.shuffle:
                rng = np.random.default_rng((self.seed, epoch))
                self._perm = rng.permutation(self._len)
            else:
                self._perm = np.arange(self._len)
            self._perm_epoch = epoch
        return self._perm

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self._offset >= self._len:
            self._epoch += 1
            self._offset = 0
            raise StopIteration
        idx = int(self._order(self._epoch)[self._offset])
        self._offset += 1
        b = self.dataset[idx]
        if self.batch_fn is not None:
            b = self.batch_fn(b)
        return self._place(b)


class RepeatingLoader:
    """Wrap an iterator to restart on exhaustion (reference:
    ``deepspeed/runtime/pipe/module.py`` RepeatingLoader used by pipeline tests)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)
