"""Device-feeding data loader.

Analog of ``deepspeed/runtime/dataloader.py`` (``DeepSpeedDataLoader``, 162 LoC,
curriculum-capable). The TPU version's job: take any host iterable of numpy/array
pytrees and hand the engine batches already placed with the input sharding
(dim 0 split over (data, fsdp)), double-buffered so host→HBM transfer overlaps step
``n`` compute (the reference gets this from CUDA streams + pin_memory).
"""
import itertools
from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np

from ..comm.topology import MeshTopology


class DSTpuDataLoader:
    def __init__(self, dataset: Iterable, topo: MeshTopology,
                 batch_fn: Optional[Callable[[Any], Any]] = None,
                 prefetch: int = 2, drop_last: bool = True):
        self.dataset = dataset
        self.topo = topo
        self.batch_fn = batch_fn
        self.prefetch = max(0, prefetch)
        self.drop_last = drop_last
        self._len = None
        try:
            self._len = len(dataset)  # type: ignore[arg-type]
        except TypeError:
            pass

    def __len__(self):
        if self._len is None:
            raise TypeError("underlying dataset has no length")
        return self._len

    def _place(self, batch):
        def put(x):
            arr = np.asarray(x)
            return jax.device_put(arr, self.topo.data_sharding(arr.ndim))

        return jax.tree_util.tree_map(put, batch)

    def __iter__(self) -> Iterator[Any]:
        it = iter(self.dataset)
        if self.batch_fn is not None:
            it = (self.batch_fn(b) for b in it)
        placed = (self._place(b) for b in it)
        if self.prefetch == 0:
            yield from placed
            return
        # simple software pipeline: keep `prefetch` batches in flight; device_put is
        # async so transfers overlap the consumer's compute.
        buf = list(itertools.islice(placed, self.prefetch))
        for nxt in placed:
            yield buf.pop(0)
            buf.append(nxt)
        yield from buf


class RepeatingLoader:
    """Wrap an iterator to restart on exhaustion (reference:
    ``deepspeed/runtime/pipe/module.py`` RepeatingLoader used by pipeline tests)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)
