"""Import-path compat: ``deepspeed.runtime.activation_checkpointing.
checkpointing`` — the reference exposes the checkpointing API at both this
nested path and ``deepspeed.checkpointing``; both resolve to the same
module here."""
from ...checkpointing import (checkpoint, configure,  # noqa: F401
                              is_configured, reset)
