"""ZeRO++ training step — quantized + hierarchical FSDP communication.

The reference turns ZeRO++ on via engine flags (``zero_quantized_weights``,
``zero_quantized_gradients``, ``zero_hpz_partition_size``; engine wiring at
``runtime/engine.py:849-858``) that reroute ZeRO-3's parameter all-gather and
gradient reduce-scatter through int8 collectives
(``partition_parameters.py:679`` CUDAQuantizer, ``coalesced_collectives.py``
``all_to_all_quant_reduce``) and add a secondary parameter partition within
the node (hpZ, ``partition_parameters.py:1551``).

XLA's automatic SPMD collectives can't be intercepted, so when these flags are
set the engine swaps its pjit train step for THIS explicit ``shard_map``
program over the (data, fsdp) mesh:

* **param gather** — each fsdp-sharded leaf is all-gathered by hand; qwZ
  ships int8 blocks + fp32 scales (``comm/quantized.quantized_all_gather``).
* **hpZ** — the gather is hierarchical: primary shards (1/N) are first
  collected across the *outer* groups (the DCN-ish hop, once per step) into a
  secondary partition of size ``h`` = ``zero_hpz_partition_size``, and the
  full tensor is then assembled from the secondary within each inner group
  (the ICI hop). Wire layout matches the reference's node-local secondary
  shard: the outer hop runs once per step, the cheap inner hop does the rest.
* **grad reduce** — per microbatch, each gradient leaf is reduce-scattered
  over fsdp; qgZ uses the int8 all-to-all + dequant-mean
  (``all_to_all_quant_reduce``); the scan accumulator is the 1/N shard.
* **update** — optimizer runs on the local shard (moments sharded
  identically), with manual global grad-norm clipping (psum of shard square
  sums — optax's ``clip_by_global_norm`` would compute a per-shard norm
  inside shard_map).
* **TP composition** — the shard_map is *partially manual*: only
  ``{data, fsdp}`` are manual axes (``axis_names=``); the ``model`` axis
  stays automatic, so inside the body every TP-sharded dim is seen at its
  global size and XLA's SPMD partitioner keeps inserting the Megatron-style
  TP collectives for the forward/backward, exactly as on the pjit path.
  This mirrors the reference's headline ZeRO++ deployment — hpZ/qwZ on top
  of Megatron TP (``partition_parameters.py:1551``, engine flags
  ``runtime/engine.py:849-858``) — without hand-writing the TP collectives.

Scope (asserted by the engine): stage 3, axes {data, fsdp, model}; pp/sp/ep
composition stays on the pjit path, where XLA owns all the collectives.
"""
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .loss_scaler import scale_loss, unscale_grads
from ..comm.quantized import all_to_all_quant_reduce, quantized_all_gather
from ..comm.comms_logging import comms_logger

AXIS = "fsdp"
MANUAL = frozenset({"data", "fsdp"})


def _manual_spec(spec) -> P:
    """Strip non-manual mesh axes from a PartitionSpec: partial-manual
    shard_map in/out specs may only name manual axes; auto axes (model, …)
    are carried by the outer jit shardings instead."""
    out = []
    for s in spec:
        axes = s if isinstance(s, tuple) else ((s,) if s else ())
        kept = tuple(a for a in axes if a in MANUAL)
        out.append(kept if len(kept) > 1 else (kept[0] if kept else None))
    return P(*out)


def _fsdp_dim(spec) -> Optional[int]:
    """Index of the dim a PartitionSpec shards over fsdp, or None."""
    for i, s in enumerate(spec):
        axes = s if isinstance(s, tuple) else (s,)
        if "fsdp" in axes:
            return i
    return None


def _inner_groups(n: int, h: int):
    """Device groups for the intra-node hop: consecutive ranks share a node."""
    return [[o * h + i for i in range(h)] for o in range(n // h)]


def _outer_groups(n: int, h: int):
    """Groups for the cross-node hop: same inner rank across nodes."""
    return [[o * h + i for o in range(n // h)] for i in range(h)]


def hierarchical_all_gather(x: jnp.ndarray, n: int, h: int,
                            quantized: bool, group_size: int) -> jnp.ndarray:
    """Two-hop hpZ gather of a dim-0-sharded leaf inside shard_map.

    ``x``: local primary shard [F/n, ...]. Hop 1 (outer, once per step):
    gather across outer groups → the secondary shard [F/h, ...] holding
    slices {o·h + inner} interleaved. Hop 2 (inner): gather secondaries
    within the node and de-interleave → full [F, ...].
    """
    if h <= 1 or h >= n:
        if quantized:
            return quantized_all_gather(x, AXIS, group_size=group_size)
        return lax.all_gather(x, AXIS, axis=0, tiled=True)
    # hop 1: secondary partition (plain wire: crosses the slow tier once)
    sec = lax.all_gather(x, AXIS, axis=0, tiled=True,
                         axis_index_groups=_outer_groups(n, h))
    # hop 2: assemble within the node
    if quantized:
        gathered = quantized_all_gather(sec, AXIS, group_size=group_size,
                                        axis_index_groups=_inner_groups(n, h))
        gathered = gathered.reshape((h,) + sec.shape)
    else:
        gathered = lax.all_gather(sec, AXIS, axis=0, tiled=False,
                                  axis_index_groups=_inner_groups(n, h))
    # gathered[i'] = concat_o slice[o·h+i']; reorder to slice[j] at row j
    shard = x.shape[0]
    full = gathered.reshape((h, n // h, shard) + x.shape[1:])
    full = jnp.moveaxis(full, 0, 1)  # [n/h, h, shard, ...]
    return full.reshape((n * shard,) + x.shape[1:])


def build_zeropp_grads_fn(engine):
    """Device half of a ZeRO++ step under ZeRO-Offload: same explicit
    gather/reduce body, but grads (still loss-scaled, fsdp-sharded layout)
    are RETURNED for the host-resident fp32 master update instead of being
    applied on device (``Engine._build_grads_batch_fn`` contract; reference
    composes ZeRO++ flags with offload through the same stage-3 engine)."""
    return build_zeropp_train_fn(engine, with_update=False)


def build_zeropp_train_fn(engine, with_update: bool = True):
    """Drop-in replacement for ``Engine._build_train_batch_fn`` output
    (or, ``with_update=False``, for ``_build_grads_batch_fn``)."""
    cfg = engine.config
    topo = engine.topology
    n = topo.axis_sizes["fsdp"]
    h = cfg.zero.zero_hpz_partition_size
    qw = cfg.zero.zero_quantized_weights
    qg = cfg.zero.zero_quantized_gradients
    gas = cfg.gradient_accumulation_steps
    group_size = 256
    clip = cfg.gradient_clipping

    is_spec = lambda x: isinstance(x, P)
    param_specs = jax.tree_util.tree_map(
        lambda s: s.spec, engine.param_shardings,
        is_leaf=lambda x: hasattr(x, "spec"))
    # under offload the optimizer state lives host-side (plain device
    # placements, or None for multi-host) — the grads-only variant never
    # touches it
    opt_specs = None
    if with_update:
        opt_specs = jax.tree_util.tree_map(
            lambda s: s.spec, engine.opt_shardings,
            is_leaf=lambda x: hasattr(x, "spec"))
    # PartitionSpec may itself be a pytree: pair leaves positionally instead
    # of tree_map-ing over mixed structures
    spec_leaves = jax.tree_util.tree_leaves(param_specs, is_leaf=is_spec)
    batch_spec = P(("data", "fsdp"))
    repl = P()
    # partial-manual shard_map: specs may only name manual axes — TP (model)
    # dims are stripped here and ride the outer jit shardings as auto axes
    manual_param_specs = jax.tree_util.tree_map(
        _manual_spec, param_specs, is_leaf=is_spec)
    manual_opt_specs = (jax.tree_util.tree_map(
        _manual_spec, opt_specs, is_leaf=is_spec)
        if opt_specs is not None else None)
    # per-device payloads of a leaf are 1/auto_factor of its global-view size
    auto_sizes = {a: s for a, s in topo.axis_sizes.items()
                  if a not in MANUAL and s > 1}

    def _auto_factor(spec):
        f = 1
        for s in spec:
            for a in (s if isinstance(s, tuple) else (s,) if s else ()):
                f *= auto_sizes.get(a, 1)
        return f

    def map_with_specs(f, tree):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        assert len(leaves) == len(spec_leaves)
        return treedef.unflatten(
            [f(x, s) for x, s in zip(leaves, spec_leaves)])

    def _wire_bytes(size, dtype, quantized):
        """Per-device payload: int8 elements + one fp32 scale per block, or
        the element dtype as-is."""
        if quantized:
            return size + (-(-size // group_size)) * 4
        return size * jnp.dtype(dtype).itemsize

    def gather_leaf(x, spec):
        k = _fsdp_dim(spec)
        if k is None:
            return x
        moved = jnp.moveaxis(x, k, 0)
        local = moved.size // _auto_factor(spec)
        comms_logger.append("zeropp_gather" + ("_int8" if qw else ""),
                            AXIS, _wire_bytes(local, moved.dtype, qw) * n,
                            tuple(moved.shape))
        full = hierarchical_all_gather(moved, n, h, qw, group_size)
        return jnp.moveaxis(full, 0, k)

    def reduce_leaf(g, spec):
        """Full-size grad leaf → this rank's mean shard over fsdp."""
        k = _fsdp_dim(spec)
        if k is None:
            return lax.pmean(g, AXIS)
        moved = jnp.moveaxis(g, k, 0)
        comms_logger.append("zeropp_reduce" + ("_int8" if qg else ""),
                            AXIS,
                            _wire_bytes(moved.size // _auto_factor(spec),
                                        moved.dtype, qg),
                            tuple(moved.shape))
        if qg:
            shard = all_to_all_quant_reduce(moved, AXIS,
                                            group_size=group_size)
        else:
            shard = lax.psum_scatter(moved, AXIS, scatter_dimension=0,
                                     tiled=True) / n
        return jnp.moveaxis(shard, 0, k)

    global_mean = lambda m: lax.pmean(lax.pmean(m, "data"), AXIS)

    def compute_gshards(params, scaler, batch, rng):
        """Shared device half: qwZ/hpZ gather → microbatch grads → qgZ
        reduce-scatter → DP mean. Grads come back still loss-SCALED (both
        consumers unscale: the fused body below, the host-offload apply)."""
        full_params = map_with_specs(gather_leaf, params)

        def micro_grads(mb, r):
            def scaled_loss(p):
                loss, metrics = engine._loss_and_metrics(p, mb, r)
                return scale_loss(loss, scaler), (loss, metrics)

            (_, (loss, metrics)), grads = jax.value_and_grad(
                scaled_loss, has_aux=True)(full_params)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32)
                if jnp.issubdtype(g.dtype, jnp.floating) else g, grads)
            # reduce to shards NOW — the accumulator carries 1/N, the
            # explicit analog of per-bucket reduce inside backward
            shards = map_with_specs(reduce_leaf, grads)
            return loss, metrics, shards

        if gas == 1:
            # raw rng matches the pjit path's gas==1 branch (engine.py) so
            # dropout masks (and therefore losses) are path-invariant
            loss, metrics, gshards = micro_grads(batch, rng)
            losses = loss[None]
        else:
            def step(carry, mb):
                acc, i = carry
                loss, metrics, shards = micro_grads(
                    mb, jax.random.fold_in(rng, i))
                acc = jax.tree_util.tree_map(jnp.add, acc, shards)
                return (acc, i + 1), (loss, metrics)

            zero = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            (gshards, _), (losses, metrics) = lax.scan(step, (zero, 0), batch)
            gshards = jax.tree_util.tree_map(lambda g: g / gas, gshards)
            metrics = jax.tree_util.tree_map(lambda m: m.mean(axis=0), metrics)
        # DP average (grads identical across fsdp shards by construction)
        gshards = jax.tree_util.tree_map(lambda g: lax.pmean(g, "data"),
                                         gshards)
        return gshards, losses, metrics

    def body(params, opt_state, scaler, batch, rng):
        gshards, losses, metrics = compute_gshards(params, scaler, batch,
                                                   rng)
        gshards = unscale_grads(gshards, scaler)

        # overflow check gated on fp16 exactly like the pjit and offload
        # paths (_apply_grads): the skip-on-overflow protocol is a loss-
        # scaler feature; bf16/fp32 training never skips
        leaves = jax.tree_util.tree_leaves(gshards)
        if engine.fp16_enabled:
            finite_local = jnp.all(jnp.stack([jnp.isfinite(g).all()
                                              for g in leaves]))
            finite = lax.pmin(finite_local.astype(jnp.int32), AXIS) > 0
        else:
            finite = jnp.asarray(True)
        # sharded leaves partition the square-sum across fsdp (psum restores
        # the global norm); replicated leaves contribute once
        dims = [_fsdp_dim(s) for s in spec_leaves]
        sq_sharded = sum((jnp.sum(jnp.square(g))
                          for g, k in zip(leaves, dims) if k is not None),
                         start=jnp.float32(0))
        sq_repl = sum((jnp.sum(jnp.square(g))
                       for g, k in zip(leaves, dims) if k is None),
                      start=jnp.float32(0))
        grad_norm = jnp.sqrt(lax.psum(sq_sharded, AXIS) + sq_repl)
        if clip and clip > 0:
            scale_f = jnp.minimum(1.0, clip / jnp.maximum(grad_norm, 1e-6))
            gshards = jax.tree_util.tree_map(lambda g: g * scale_f, gshards)

        new_params, new_opt, new_scaler = engine._finish_update(
            params, opt_state, scaler, gshards, finite)
        # user metrics are shard-local batch means — reduce like the loss
        out_metrics = {
            **jax.tree_util.tree_map(global_mean, metrics),
            "loss": global_mean(losses.mean()),
            "grad_norm": grad_norm,
            "finite": finite,
            "loss_scale": new_scaler.scale,
        }
        return new_params, new_opt, new_scaler, out_metrics

    def make_batch_spec(x):
        nd = np.ndim(x)
        lead = (None, batch_spec[0]) if gas > 1 else (batch_spec[0],)
        if nd < len(lead):
            # scalar side-channels riding the batch (e.g. pld_theta: () or
            # a (gas,) vector) replicate — they carry no batch dimension
            return P(*([None] * nd))
        return P(*lead, *([None] * (nd - len(lead))))

    if not with_update:
        def grads_body(params, scaler, batch, rng):
            gshards, losses, metrics = compute_gshards(params, scaler,
                                                       batch, rng)
            metrics = jax.tree_util.tree_map(global_mean, metrics)
            return gshards, global_mean(losses), metrics

        def grads_fn(params, scaler, batch, rng):
            batch_specs = jax.tree_util.tree_map(make_batch_spec, batch)
            mapped = jax.shard_map(
                grads_body, mesh=topo.mesh,
                in_specs=(manual_param_specs, repl, batch_specs, repl),
                out_specs=(manual_param_specs, repl, repl),
                axis_names=MANUAL,
                check_vma=False)
            gshards, losses, metrics = mapped(params, scaler, batch, rng)
            # pin the auto (TP) dims like the sibling paths do — the
            # multi-host offload consumer pairs gradient blocks to master
            # shards by exact shard-index keys from grad_shardings, so the
            # layout must not be left to XLA inference
            if engine.grad_shardings is not None:
                gshards = jax.lax.with_sharding_constraint(
                    gshards, engine.grad_shardings)
            return gshards, losses, metrics

        return jax.jit(grads_fn)

    def fn(params, opt_state, scaler, batch, rng):
        batch_specs = jax.tree_util.tree_map(make_batch_spec, batch)
        mapped = jax.shard_map(
            body, mesh=topo.mesh,
            # P() prefixes: scaler/rng inputs and the scaler/metrics outputs
            # replicate; their tree structure is whatever the body returns
            in_specs=(manual_param_specs, manual_opt_specs, repl,
                      batch_specs, repl),
            out_specs=(manual_param_specs, manual_opt_specs, repl, repl),
            axis_names=MANUAL,
            check_vma=False)
        new_p, new_o, new_s, metrics = mapped(params, opt_state, scaler,
                                              batch, rng)
        # pin the auto (TP) dims of the outputs back to the engine layout so
        # the donated buffers round-trip with no per-step resharding
        new_p = jax.lax.with_sharding_constraint(new_p, engine.param_shardings)
        new_o = jax.lax.with_sharding_constraint(new_o, engine.opt_shardings)
        return new_p, new_o, new_s, metrics

    return jax.jit(fn, donate_argnums=(0, 1, 2))
