"""TiledLinear — split huge linears into sequentially-processed tiles.

Analog of the reference's ``runtime/zero/tiling.py`` (``TiledLinear``, 296
LoC): a linear so large that materializing its full gathered weight (or its
full output) at once would blow device memory is computed tile-by-tile. In
the reference this exists so ZeRO-3 can partition single enormous layers;
here the same effect comes from slicing the (fsdp-sharded) weight inside a
``lax.scan`` — under SPMD each iteration all-gathers only one tile's worth
of weight, so the working set is ``full_weight / splits`` instead of the
whole matrix.
"""
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["tiled_linear", "TiledLinear"]


def tiled_linear(x: jnp.ndarray, w: jnp.ndarray,
                 bias: Optional[jnp.ndarray] = None,
                 in_splits: int = 1, out_splits: int = 1) -> jnp.ndarray:
    """``x [..., In] @ w [In, Out] (+ bias)`` with the contraction and/or
    output dimension processed in sequential tiles.

    ``in_splits``: the In axis is cut into tiles whose partial products
    accumulate in fp32 — peak live weight is ``In/in_splits × Out``.
    ``out_splits``: the Out axis is produced tile-by-tile and concatenated —
    bounds the live weight to ``In × Out/out_splits`` per step.
    """
    n_in, n_out = w.shape
    if n_in % in_splits or n_out % out_splits:
        raise ValueError(f"weight {w.shape} not divisible into "
                         f"({in_splits}, {out_splits}) tiles")
    ti, to = n_in // in_splits, n_out // out_splits

    def out_tile(oj):
        w_o = jax.lax.dynamic_slice_in_dim(w, oj * to, to, axis=1)
        if in_splits == 1:
            return jnp.einsum("...i,io->...o", x, w_o)

        def body(acc, ii):
            w_t = jax.lax.dynamic_slice_in_dim(w_o, ii * ti, ti, axis=0)
            x_t = jax.lax.dynamic_slice_in_dim(x, ii * ti, ti, axis=-1)
            return acc + jnp.einsum("...i,io->...o",
                                    x_t.astype(jnp.float32),
                                    w_t.astype(jnp.float32)), None

        acc0 = jnp.zeros(x.shape[:-1] + (to,), jnp.float32)
        acc, _ = jax.lax.scan(body, acc0, jnp.arange(in_splits))
        return acc.astype(x.dtype)

    if out_splits == 1:
        out = out_tile(0)
    else:

        def obody(_, oj):
            return None, out_tile(oj)

        _, tiles = jax.lax.scan(obody, None, jnp.arange(out_splits))
        # tiles: [out_splits, ..., to] → [..., Out]
        out = jnp.moveaxis(tiles, 0, -2).reshape(x.shape[:-1] + (n_out,))
    if bias is not None:
        out = out + bias.astype(out.dtype)
    return out


class TiledLinear:
    """Module-style surface matching the reference's ``TiledLinear(in_f,
    out_f, ...)`` constructor: owns its weight/bias and applies
    :func:`tiled_linear` on call."""

    def __init__(self, in_features: int, out_features: int,
                 in_splits: int = 1, out_splits: int = 1, bias: bool = True,
                 seed: int = 0, init_scale: float = 0.02):
        import jax.random as jrandom

        self.in_splits = in_splits
        self.out_splits = out_splits
        k = jrandom.PRNGKey(seed)
        self.weight = jrandom.normal(
            k, (in_features, out_features), jnp.float32) * init_scale
        self.bias = jnp.zeros((out_features,), jnp.float32) if bias else None

    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        return tiled_linear(x, self.weight, self.bias,
                            in_splits=self.in_splits,
                            out_splits=self.out_splits)
