"""Multi-controller ZeRO-Offload: per-host shard-swapping CPU Adam.

Reference analog: ``DeepSpeedCPUAdam`` (``csrc/adam/cpu_adam.cpp``) driven
per rank by the ZeRO partitioned optimizers — each rank owns its
partition's fp32 master + Adam moments on its OWN host, updates them after
the sharded gradients land (``runtime/zero/stage_1_and_2.py`` cpu_offload,
``stage3.py:1816`` swap-in), and the global gradient norm is finished with a
cross-rank allreduce
(``stage_1_and_2.py complete_grad_norm_calculation_for_cpu_offload``).

TPU-native shape of the same idea: gradients arrive as GLOBAL jax arrays in
the ZeRO-3 (fsdp-sharded) layout; every controller pulls only its
ADDRESSABLE shards to host numpy, runs the fp32 AdamW partition update
there, and rebuilds a global fp32 array from the updated local shards with
``jax.make_array_from_single_device_arrays``. The engine then casts/reshards
that back to the working-param layout with one jitted identity, so any
cross-host gather rides ICI/DCN on device — never the hosts.

Like the reference (CPUAdam is the only offload optimizer), this path
implements Adam/AdamW; other optimizer types raise at engine init.
"""
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from .loss_scaler import LossScaleState, host_update_loss_scale
from ..utils.logging import log_dist

__all__ = ["MultiHostCPUAdam"]


def _idx_key(index) -> str:
    return repr(index)


class MultiHostCPUAdam:
    """Per-host fp32 master + Adam moments over the addressable shards of a
    ZeRO-3-layout parameter tree."""

    def __init__(self, placed_params: Any, shard_shardings: Any, *,
                 betas: Tuple[float, float], eps: float, weight_decay: float,
                 clip: Optional[float], lr_fn: Callable[[int], float],
                 fp16_cfg=None, fp16_enabled: bool = False, swapper=None):
        self.b1, self.b2 = betas
        self.eps = eps
        self.wd = weight_decay
        self.clip = clip
        self.lr_fn = lr_fn
        self.fp16_cfg = fp16_cfg
        self.fp16_enabled = fp16_enabled
        self.shard_shardings = shard_shardings
        self.step_count = 0
        # ZeRO-Infinity across controllers: with a swapper, each host's
        # Adam moments live on ITS NVMe between steps (the reference's
        # per-rank optimizer-state swap, stage3.py:1816 — every rank swaps
        # its own partition); the fp32 master stays in host RAM because
        # the param push-back needs it every step either way.
        self.swapper = swapper

        # Stage the params into the shard (ZeRO-3) layout once, on device —
        # XLA does the resharding collectives — then pull local shards.
        leaves, self._treedef = jax.tree_util.tree_flatten(placed_params)
        sh_leaves = jax.tree_util.tree_leaves(shard_shardings)
        staged = jax.jit(lambda t: t, out_shardings=sh_leaves)(leaves)
        # per leaf: {index_key: fp32 np shard}, plus the device->index map
        self.master: list = []
        self.m: list = []
        self.v: list = []
        self._dev_index: list = []   # per leaf: {device: index}
        self._shapes: list = []
        for leaf, sh in zip(staged, sh_leaves):
            dmap = sh.addressable_devices_indices_map(leaf.shape)
            self._dev_index.append(dmap)
            self._shapes.append(leaf.shape)
            shards: Dict[str, np.ndarray] = {}
            for s in leaf.addressable_shards:
                k = _idx_key(s.index)
                if k not in shards:
                    # np.array (copy): jax buffers are read-only views and
                    # the update mutates the master in place. Floating
                    # leaves promote to the fp32 master; integer leaves
                    # keep their dtype (and are skipped by the update).
                    a = np.array(s.data)
                    if np.issubdtype(a.dtype, np.floating):
                        a = a.astype(np.float32)
                    shards[k] = a
            self.master.append(shards)
            self.m.append({k: np.zeros_like(a) for k, a in shards.items()})
            self.v.append({k: np.zeros_like(a) for k, a in shards.items()})
        n_local = sum(a.nbytes for d in self.master for a in d.values())
        # only floating leaves' moments are ever updated (the step loop
        # skips integer leaves) — they are the only ones worth swapping,
        # and swapping others would leak never-retrieved prefetch requests
        self._swap_keys = [
            {k for k, a in shards.items()
             if np.issubdtype(a.dtype, np.floating)}
            for shards in self.master]
        if self.swapper is not None:
            self._offload_moments()
        log_dist(f"multi-host offload: {len(self.master)} tensors, "
                 f"{n_local / 1e6:.1f} MB fp32 master per host, "
                 f"{jax.process_count()} hosts"
                 + (f"; moments on NVMe ({self.swapper.swap_dir})"
                    if self.swapper is not None else ""))

    # ------------------------------------------------------------- nvme swap
    def _offload_moments(self) -> None:
        """Floating moments → NVMe; drop the host copies (dict KEYS are
        kept — they are the swap names and the iteration domain)."""
        for which, store in (("m", self.m), ("v", self.v)):
            for li, d in enumerate(store):
                for k in self._swap_keys[li]:
                    if d[k] is not None:
                        self.swapper.swap_out(f"{which}/{li}/{k}", d[k])
                        d[k] = None

    def _moment_store(self, which: str):
        """Materialized moment shards (checkpointing); files stay valid."""
        store = self.m if which == "m" else self.v
        if self.swapper is None:
            return store
        out = []
        for li, d in enumerate(store):
            for k in self._swap_keys[li]:
                self.swapper.prefetch(f"{which}/{li}/{k}")
            out.append({k: (self.swapper.retrieve(f"{which}/{li}/{k}")
                            if k in self._swap_keys[li] else d[k])
                        for k in d})
        return out

    def moments_template_tree(self) -> Dict[str, Any]:
        """Shape/dtype-faithful ZERO moments in the shard layout — the
        checkpoint-restore template. Moments are zeros_like the master, so
        no NVMe read is needed just to know shapes (a real-scale restore
        must not pay a full optimizer-state disk read for a template)."""
        zeros = [{k: np.zeros_like(a) for k, a in shards.items()}
                 for shards in self.master]
        return {"m": self._assemble(zeros), "v": self._assemble(zeros),
                "step": np.asarray(self.step_count, np.int32)}

    # ------------------------------------------------------------------ step
    def step(self, grads: Any, scaler: LossScaleState
             ) -> Tuple[Any, LossScaleState, Dict[str, Any]]:
        """One partition update. ``grads``: global arrays in the shard
        layout (scaled by ``scaler.scale``). Returns (global fp32 master
        tree in shard layout, new scaler state, metrics)."""
        if self.swapper is not None:
            # begin the disk reads NOW — they overlap the grad-shard pull
            # and the cross-host norm allreduce below
            for which in ("m", "v"):
                for li, keys in enumerate(self._swap_keys):
                    for k in keys:
                        self.swapper.prefetch(f"{which}/{li}/{k}")
        g_leaves = jax.tree_util.tree_leaves(grads)
        # the scaler state is HOST-resident on this path (the engine
        # converts it at init / checkpoint load via host_loss_scale_state):
        # reading the scale is a plain float, not a per-step device sync
        scale = float(scaler.scale)
        local_g: list = []
        sq = 0.0
        finite = True
        for leaf in g_leaves:
            shards: Dict[str, np.ndarray] = {}
            for s in leaf.addressable_shards:
                k = _idx_key(s.index)
                need_store = k not in shards
                # the norm counts every replica-0 shard even when another
                # local replica already filled the store — skipping it
                # would silently drop the block from the global norm
                if not need_store and s.replica_id != 0:
                    continue
                g = np.asarray(s.data, dtype=np.float32) / scale
                if need_store:
                    shards[k] = g
                if s.replica_id == 0:
                    # each logical block counted exactly once globally
                    sq += float((g * g).sum())
                    finite = finite and bool(np.isfinite(g).all())
            local_g.append(shards)

        # finish the norm / overflow check across hosts (the reference's
        # cpu-offload grad-norm allreduce)
        sq, finite = self._allreduce_host(sq, finite)
        grad_norm = float(np.sqrt(sq))

        clip_f = 1.0
        if self.clip and self.clip > 0 and grad_norm > self.clip:
            clip_f = self.clip / max(grad_norm, 1e-6)

        if finite:
            self.step_count += 1
            t = self.step_count
            lr = float(self.lr_fn(t - 1))
            bc1 = 1.0 - self.b1 ** t
            bc2 = 1.0 - self.b2 ** t
            for li, (p_d, m_d, v_d, g_d) in enumerate(
                    zip(self.master, self.m, self.v, local_g)):
                for k, g in g_d.items():
                    g = g * clip_f
                    p = p_d[k]
                    if not np.issubdtype(p.dtype, np.floating):
                        continue
                    if self.swapper is not None:
                        m = self.swapper.retrieve(f"m/{li}/{k}")
                        v = self.swapper.retrieve(f"v/{li}/{k}")
                    else:
                        m, v = m_d[k], v_d[k]
                    m *= self.b1
                    m += (1 - self.b1) * g
                    v *= self.b2
                    v += (1 - self.b2) * g * g
                    upd = (m / bc1) / (np.sqrt(v / bc2) + self.eps)
                    if self.wd:
                        upd = upd + self.wd * p  # AdamW decoupled decay
                    p -= lr * upd
                    if self.swapper is not None:
                        self.swapper.swap_out(f"m/{li}/{k}", m)
                        self.swapper.swap_out(f"v/{li}/{k}", v)

        fp16 = self.fp16_cfg
        # host-side transition (loss_scaler.host_update_loss_scale): same
        # state machine as the jitted path, zero device work
        new_scaler = host_update_loss_scale(
            scaler, finite,
            dynamic=bool(self.fp16_enabled and fp16 is not None
                         and fp16.dynamic),
            scale_window=(fp16.loss_scale_window if fp16 else 1000),
            min_scale=(fp16.min_loss_scale if fp16 else 1.0),
            hysteresis=(fp16.hysteresis if fp16 else 2))
        metrics = {"grad_norm": grad_norm, "finite": finite,
                   "loss_scale": float(new_scaler.scale)}
        return self.master_global_tree(), new_scaler, metrics

    # ---------------------------------------------------------------- helpers
    def _allreduce_host(self, sq: float, finite: bool
                        ) -> Tuple[float, bool]:
        if jax.process_count() == 1:
            return sq, finite
        from jax.experimental import multihost_utils

        vals = multihost_utils.process_allgather(
            np.asarray([sq, 1.0 if finite else 0.0], np.float64))
        return float(vals[:, 0].sum()), bool(vals[:, 1].min() > 0.5)

    def _assemble(self, store) -> Any:
        """Per-host shards → global arrays in the shard layout (cheap —
        local device_puts only; replicas reuse their index's shard)."""
        sh_leaves = jax.tree_util.tree_leaves(self.shard_shardings)
        out = []
        for shards, sh, dmap, shape in zip(store, sh_leaves,
                                           self._dev_index, self._shapes):
            arrs = [jax.device_put(shards[_idx_key(idx)], d)
                    for d, idx in dmap.items()]
            out.append(jax.make_array_from_single_device_arrays(
                shape, sh, arrs))
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def master_global_tree(self) -> Any:
        """The fp32 master as GLOBAL arrays in the shard layout (used for
        the param push-back and multi-controller checkpointing via orbax)."""
        return self._assemble(self.master)

    def moments_global_tree(self) -> Dict[str, Any]:
        """Adam moments as global arrays (checkpoint payload)."""
        return {"m": self._assemble(self._moment_store("m")),
                "v": self._assemble(self._moment_store("v")),
                "step": np.asarray(self.step_count, np.int32)}

    def load_state(self, master_tree: Any, moments: Optional[Dict[str, Any]]
                   ) -> None:
        """Restore from global arrays (resharding handled by the caller's
        checkpoint engine restoring into ``shard_shardings``)."""
        def pull(tree, store):
            for i, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
                shards: Dict[str, np.ndarray] = {}
                for s in leaf.addressable_shards:
                    k = _idx_key(s.index)
                    if k not in shards:
                        a = np.array(s.data)   # writable copy
                        if np.issubdtype(a.dtype, np.floating):
                            a = a.astype(np.float32)
                        shards[k] = a          # ints keep their dtype
                store[i] = shards

        pull(master_tree, self.master)
        if moments is not None:
            pull(moments["m"], self.m)
            pull(moments["v"], self.v)
            self.step_count = int(np.asarray(moments["step"]))
            if self.swapper is not None:
                self._offload_moments()  # restored moments back to NVMe
