"""Hierarchical ZeRO-Offload: bucketed, pipelined per-host CPU Adam.

Reference analog: ``DeepSpeedCPUAdam`` (``csrc/adam/cpu_adam.cpp``) driven
per rank by the ZeRO partitioned optimizers — each rank owns its
partition's fp32 master + Adam moments on its OWN host, updates them after
the sharded gradients land (``runtime/zero/stage_1_and_2.py`` cpu_offload,
``stage3.py:1816`` swap-in), and the global gradient norm is finished with a
cross-rank allreduce
(``stage_1_and_2.py complete_grad_norm_calculation_for_cpu_offload``).

TPU-native shape of the same idea: gradients arrive as GLOBAL jax arrays in
the ZeRO-3 (fsdp-sharded) layout; every controller pulls only its
ADDRESSABLE shards to host numpy, runs the fp32 AdamW partition update
there, and rebuilds a global array from the updated local shards with
``jax.make_array_from_single_device_arrays``. The engine then casts/reshards
that back to the working-param layout with one jitted identity, so any
cross-host gather rides ICI/DCN on device — never the hosts.

The host phase is a **bucketed pipeline** (ZeRO-Infinity's
bandwidth-centric design, ``runtime/offload_pipeline.py``): the shard tree
is partitioned into size-targeted buckets; every grad shard's D2H pull is
issued asynchronously up front (``ShardPull`` — non-blocking device_put
with delayed wait) and the cross-host grad-norm allreduce is hoisted so
only the scalar clip factor serializes; then per bucket the fp32 Adam
update runs on a worker thread while the main thread waits the NEXT
bucket's inputs and pushes the PREVIOUS bucket's updated master back to
the device — bucket i+1's pull runs under bucket i's compute, bucket
i−1's H2D push runs under both. Under NVMe offload the Adam moments ride
a bounded double-buffered :class:`~.offload_pipeline.MomentWindow`
(prefetch ahead, write-back behind, host copies dropped on retirement),
so host-RAM high-water is bounded by the window, not the moment store.

Runs on any controller count: with one process the allreduce degenerates
to identity and the same pipeline serves single-host ZeRO-Offload (the
engine routes ``offload_*`` configs here whenever the optimizer is
Adam-family and ``pipeline`` is on). Like the reference (CPUAdam is the
only offload optimizer), this path implements Adam/AdamW; other optimizer
types use the legacy jitted host path or raise at engine init.
"""
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from .loss_scaler import LossScaleState, host_update_loss_scale
from .offload_pipeline import (DEFAULT_BUCKET_BYTES, Bucket, MomentWindow,
                               OffloadStats, ShardPull, overlap_efficiency,
                               plan_buckets)
from ..utils.logging import log_dist

__all__ = ["MultiHostCPUAdam"]


def _idx_key(index) -> str:
    return repr(index)


class MultiHostCPUAdam:
    """Per-host fp32 master + Adam moments over the addressable shards of a
    ZeRO-layout parameter tree, updated through a bucketed D2H / host-Adam /
    H2D pipeline."""

    def __init__(self, placed_params: Any, shard_shardings: Any, *,
                 betas: Tuple[float, float], eps: float, weight_decay: float,
                 clip: Optional[float], lr_fn: Callable[[int], float],
                 fp16_cfg=None, fp16_enabled: bool = False, swapper=None,
                 bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                 window_buckets: int = 2, overlap: bool = True,
                 push_dtype: Any = None):
        self.b1, self.b2 = betas
        self.eps = eps
        self.wd = weight_decay
        self.clip = clip
        self.lr_fn = lr_fn
        self.fp16_cfg = fp16_cfg
        self.fp16_enabled = fp16_enabled
        self.shard_shardings = shard_shardings
        self.step_count = 0
        # ZeRO-Infinity across controllers: with a swapper, each host's
        # Adam moments live on ITS NVMe between steps (the reference's
        # per-rank optimizer-state swap, stage3.py:1816 — every rank swaps
        # its own partition); the fp32 master stays in host RAM because
        # the param push-back needs it every step either way.
        self.swapper = swapper
        # pipeline knobs (offload_pipeline.py): transfer/compute unit size,
        # NVMe prefetch window depth, and whether the host Adam runs on a
        # worker thread (overlap=False executes the identical math inline —
        # the bit-parity reference arm)
        self.bucket_bytes = int(bucket_bytes)
        self.window_buckets = max(1, int(window_buckets))
        self.overlap = bool(overlap)
        # compute-dtype H2D push: the device working copy is compute dtype
        # anyway, so casting on the host HALVES push-back bytes vs moving
        # the fp32 master (the master itself stays exact fp32 host-side);
        # fp32 compute keeps the master arrays as-is (no pointless copy)
        self.push_dtype = (None if push_dtype is None
                           or np.dtype(push_dtype) == np.float32
                           else np.dtype(push_dtype))
        self._host_device = jax.local_devices(backend="cpu")[0]
        #: last step's OffloadStats dict (engine telemetry pulls it) and
        #: run-cumulative totals (bench reads effective bandwidths off it)
        self.last_stats: Optional[Dict[str, Any]] = None
        self.totals: Dict[str, float] = {}

        # Stage the params into the shard (ZeRO-3) layout once, on device —
        # XLA does the resharding collectives — then pull local shards.
        leaves, self._treedef = jax.tree_util.tree_flatten(placed_params)
        sh_leaves = jax.tree_util.tree_leaves(shard_shardings)
        staged = jax.jit(lambda t: t, out_shardings=sh_leaves)(leaves)
        # per leaf: {index_key: fp32 np shard}, plus the device->index map
        self.master: list = []
        self.m: list = []
        self.v: list = []
        self._dev_index: list = []   # per leaf: {device: index}
        self._shapes: list = []
        for leaf, sh in zip(staged, sh_leaves):
            dmap = sh.addressable_devices_indices_map(leaf.shape)
            self._dev_index.append(dmap)
            self._shapes.append(leaf.shape)
            shards: Dict[str, np.ndarray] = {}
            for s in leaf.addressable_shards:
                k = _idx_key(s.index)
                if k not in shards:
                    # np.array (copy): jax buffers are read-only views and
                    # the update mutates the master in place. Floating
                    # leaves promote to the fp32 master; integer leaves
                    # keep their dtype (and are skipped by the update).
                    a = np.array(s.data)
                    if np.issubdtype(a.dtype, np.floating):
                        a = a.astype(np.float32)
                    shards[k] = a
            self.master.append(shards)
            self.m.append({k: np.zeros_like(a) for k, a in shards.items()})
            self.v.append({k: np.zeros_like(a) for k, a in shards.items()})
        n_local = sum(a.nbytes for d in self.master for a in d.values())
        # only floating leaves' moments are ever updated (the step loop
        # skips integer leaves) — they are the only ones worth swapping,
        # and swapping others would leak never-retrieved prefetch requests
        self._swap_keys = [
            {k for k, a in shards.items()
             if np.issubdtype(a.dtype, np.floating)}
            for shards in self.master]
        # size-targeted bucket plan over the floating shards, in leaf order
        # (leaf order is the H2D first-use order): the unit of D2H wait,
        # host compute, H2D push and moment prefetch/write-back
        items = [(li, k, self.master[li][k].nbytes)
                 for li in range(len(self.master))
                 for k in sorted(self._swap_keys[li])]
        self.buckets: List[Bucket] = plan_buckets(items, self.bucket_bytes)
        self._window: Optional[MomentWindow] = None
        if self.swapper is not None:
            self._offload_moments()
            self._window = MomentWindow(self.swapper, self.buckets,
                                        window=self.window_buckets)
        log_dist(f"multi-host offload: {len(self.master)} tensors in "
                 f"{len(self.buckets)} bucket(s) "
                 f"(target {self.bucket_bytes / 2**20:.0f} MiB), "
                 f"{n_local / 1e6:.1f} MB fp32 master per host, "
                 f"{jax.process_count()} hosts, "
                 f"overlap={'on' if self.overlap else 'off'}"
                 + (f"; moments on NVMe ({self.swapper.swap_dir}, "
                    f"window={self.window_buckets} buckets)"
                    if self.swapper is not None else ""))

    # ------------------------------------------------------------- nvme swap
    def _offload_moments(self) -> None:
        """Floating moments → NVMe; drop the host copies (dict KEYS are
        kept — they are the swap names and the iteration domain)."""
        for which, store in (("m", self.m), ("v", self.v)):
            for li, d in enumerate(store):
                for k in self._swap_keys[li]:
                    if d[k] is not None:
                        self.swapper.swap_out(f"{which}/{li}/{k}", d[k])
                        d[k] = None

    def _moment_store(self, which: str):
        """Materialized moment shards (checkpointing). The DISK READS ride
        a one-leaf look-ahead so in-flight IO stays bounded, but the
        returned store IS fully materialized — the checkpoint engine
        serializes one global tree, so a save's host high-water is still
        ~the moment store (a per-leaf streaming save is the open half of
        the beyond-HBM ROADMAP item; the bounded-window guarantee holds
        for the STEP path, not the save). The files stay valid (a
        retrieve consumes the read, not the entry)."""
        store = self.m if which == "m" else self.v
        if self.swapper is None:
            return store
        out = []
        for li, d in enumerate(store):
            # current leaf's reads first (iterations past the first find
            # them already in flight), THEN the look-ahead — the other
            # order would queue leaf 0's reads behind leaf 1's whole batch
            for k in self._swap_keys[li]:
                self.swapper.prefetch(f"{which}/{li}/{k}")
            if li + 1 < len(store):
                for k in self._swap_keys[li + 1]:
                    self.swapper.prefetch(f"{which}/{li + 1}/{k}")
            out.append({k: (self.swapper.retrieve(f"{which}/{li}/{k}")
                            if k in self._swap_keys[li] else d[k])
                        for k in d})
        return out

    def moments_template_tree(self) -> Dict[str, Any]:
        """Shape/dtype-faithful ZERO moments in the shard layout — the
        checkpoint-restore template. Moments are zeros_like the master, so
        no NVMe read is needed just to know shapes (a real-scale restore
        must not pay a full optimizer-state disk read for a template)."""
        zeros = [{k: np.zeros_like(a) for k, a in shards.items()}
                 for shards in self.master]
        return {"m": self._assemble(zeros), "v": self._assemble(zeros),
                "step": np.asarray(self.step_count, np.int32)}

    # ------------------------------------------------------------------ step
    def step(self, grads: Any, scaler: LossScaleState
             ) -> Tuple[Any, LossScaleState, Dict[str, Any]]:
        """One pipelined partition update. ``grads``: global arrays in the
        shard layout (scaled by ``scaler.scale``). Returns (global master
        tree in shard layout — compute/push dtype on update steps — new
        scaler state, metrics)."""
        stats = OffloadStats(n_buckets=len(self.buckets))
        if self._window is not None:
            # begin the disk reads for the first window NOW — they overlap
            # the async grad-shard pulls and the norm phase below; the rest
            # of the store streams behind the bucket loop, never all at once
            self._window.begin_step(stats)
        g_leaves = jax.tree_util.tree_leaves(grads)
        # the scaler state is HOST-resident on this path (the engine
        # converts it at init / checkpoint load via host_loss_scale_state):
        # reading the scale is a plain float, not a per-step device sync
        scale = float(scaler.scale)

        # ---- drain the device half FIRST, booked as device_wait_s (not
        # transfer stall): under async dispatch the grads program is still
        # running when step() is entered, and no D2H byte can move before
        # it finishes — the first pull's wait would otherwise absorb the
        # whole device compute and poison the overlap ledger. The NVMe
        # window's reads (issued above) genuinely progress under this wait.
        t_dev = time.perf_counter()
        jax.block_until_ready(g_leaves)  # dslint: allow(host-sync-in-step-path) sanctioned offload seam: device-half drain, measured
        stats.extra["device_wait_s"] = time.perf_counter() - t_dev

        # ---- async D2H: issue EVERY local grad-shard pull up front (the
        # norm needs them all anyway); ShardPull.wait below is the only
        # blocking point and books exposed vs total transfer time
        pulls: List[Dict[str, ShardPull]] = []
        norm_keys: List[set] = []
        for leaf, keys in zip(g_leaves, self._swap_keys):
            d: Dict[str, ShardPull] = {}
            norm: set = set()
            for s in leaf.addressable_shards:
                k = _idx_key(s.index)
                if k not in keys:
                    continue  # integer leaves are never updated
                if s.replica_id == 0:
                    # each logical block counted exactly once globally
                    norm.add(k)
                if k not in d:
                    d[k] = ShardPull(s.data, self._host_device)
            pulls.append(d)
            norm_keys.append(norm)

        # ---- norm phase: wait the pulls in bucket order, unscale, and
        # accumulate the local square-sum as each bucket lands
        local_g: Dict[Tuple[int, str], np.ndarray] = {}
        sq = 0.0
        finite = True
        for b in self.buckets:
            for li, k, _ in b.items:
                g = np.asarray(pulls[li].pop(k).wait(stats),
                               np.float32) / scale
                local_g[(li, k)] = g
                if k in norm_keys[li]:
                    sq += float((g * g).sum())
                    finite = finite and bool(np.isfinite(g).all())

        # finish the norm / overflow check across hosts (the reference's
        # cpu-offload grad-norm allreduce) — hoisted to ONE collective per
        # step so only the scalar clip factor serializes the bucket loop
        sq, finite = self._allreduce_host(sq, finite)
        grad_norm = float(np.sqrt(sq))

        clip_f = 1.0
        if self.clip and self.clip > 0 and grad_norm > self.clip:
            clip_f = self.clip / max(grad_norm, 1e-6)

        pushed: List[Dict[Any, Any]] = [dict() for _ in self.master]
        if finite:
            self.step_count += 1
            t = self.step_count
            lr = float(self.lr_fn(t - 1))
            bc1 = 1.0 - self.b1 ** t
            bc2 = 1.0 - self.b2 ** t
            # ---- bucket pipeline: worker computes bucket i while the main
            # thread waits bucket i+1's moments and pushes bucket i-1 H2D.
            # The 1-thread pool is per step so engines never leak an idle
            # worker (they have no teardown of their own); spawn cost is
            # microseconds against a bucket of fp32 Adam.
            pool = (ThreadPoolExecutor(max_workers=1,
                                       thread_name_prefix="dstpu-offload")
                    if self.overlap else None)
            try:
                prev: Optional[Tuple[Bucket, Any]] = None
                for b in self.buckets:
                    mom = None
                    if self._window is not None:
                        self._window.ensure(b.index, stats)
                        mom = self._window.retrieve(b.index, stats)
                    args = (b, local_g, mom, clip_f, lr, bc1, bc2)
                    fut = (pool.submit(self._update_bucket, *args)
                           if pool is not None
                           else _Done(self._update_bucket(*args)))
                    if prev is not None:
                        self._finish_bucket(prev, pushed, stats)
                    prev = (b, fut)
                if prev is not None:
                    self._finish_bucket(prev, pushed, stats)
            finally:
                if pool is not None:
                    pool.shutdown(wait=True)
        out_tree = self._assemble_pushed(pushed, stats)

        fp16 = self.fp16_cfg
        # host-side transition (loss_scaler.host_update_loss_scale): same
        # state machine as the jitted path, zero device work
        new_scaler = host_update_loss_scale(
            scaler, finite,
            dynamic=bool(self.fp16_enabled and fp16 is not None
                         and fp16.dynamic),
            scale_window=(fp16.loss_scale_window if fp16 else 1000),
            min_scale=(fp16.min_loss_scale if fp16 else 1.0),
            hysteresis=(fp16.hysteresis if fp16 else 2))
        if self._window is not None:
            stats.window_hwm_bytes = self._window.hwm_bytes
        self.last_stats = stats.as_dict()
        stats.merge_into(self.totals)
        metrics = {"grad_norm": grad_norm, "finite": finite,
                   "loss_scale": float(new_scaler.scale)}
        return out_tree, new_scaler, metrics

    # ------------------------------------------------------ pipeline stages
    def _update_bucket(self, bucket: Bucket,
                       local_g: Dict[Tuple[int, str], np.ndarray],
                       mom, clip_f: float, lr: float, bc1: float, bc2: float
                       ) -> Tuple[Dict[Tuple[int, str], np.ndarray], float]:
        """Host fp32 AdamW over one bucket (worker thread: numpy ONLY — no
        jax calls off the main thread). Mutates master/moments in place;
        returns the per-shard push arrays (compute dtype when configured)
        and the bucket's compute seconds."""
        t0 = time.perf_counter()
        out: Dict[Tuple[int, str], np.ndarray] = {}
        for li, k, _ in bucket.items:
            g = local_g.pop((li, k)) * clip_f
            p = self.master[li][k]
            if mom is not None:
                m, v = mom[(li, k)]
            else:
                m, v = self.m[li][k], self.v[li][k]
            m *= self.b1
            m += (1 - self.b1) * g
            v *= self.b2
            v += (1 - self.b2) * g * g
            upd = (m / bc1) / (np.sqrt(v / bc2) + self.eps)
            if self.wd:
                upd = upd + self.wd * p  # AdamW decoupled decay
            p -= lr * upd
            # the push array must be a COPY: jax.device_put may zero-copy
            # an aligned host buffer, and the master is mutated in place
            # again next step (astype always copies)
            out[(li, k)] = p.astype(self.push_dtype if self.push_dtype
                                    is not None else np.float32)
        return out, time.perf_counter() - t0

    def _finish_bucket(self, prev: Tuple[Bucket, Any], pushed: list,
                       stats: OffloadStats) -> None:
        """Collect a bucket's host update and issue its H2D push (async
        ``jax.device_put`` per addressable device — replicas reuse their
        index's shard), then retire its moments behind the compute."""
        bucket, fut = prev
        out, secs = fut.result()
        stats.host_compute_s += secs
        t_issue = time.perf_counter()
        for li, k, _ in bucket.items:
            arr = out[(li, k)]
            for d, idx in self._dev_index[li].items():
                if _idx_key(idx) == k:
                    pushed[li][d] = (jax.device_put(arr, d), t_issue)
                    stats.h2d_bytes += arr.nbytes
        if self._window is not None:
            self._window.retire(bucket.index, stats)

    def _assemble_pushed(self, pushed: list, stats: OffloadStats) -> Any:
        """Global arrays in the shard layout from the per-bucket pushes;
        shards the pipeline never touched (integer leaves, overflow-skipped
        steps) push from the master now. The final block books the exposed
        H2D tail — by push time the transfers have been in flight for
        whole buckets, so it is normally near zero (and the engine's jitted
        cast/reshard would wait on them anyway)."""
        sh_leaves = jax.tree_util.tree_leaves(self.shard_shardings)
        out = []
        first_issue: Optional[float] = None
        for li, (sh, dmap, shape) in enumerate(
                zip(sh_leaves, self._dev_index, self._shapes)):
            arrs = []
            for d, idx in dmap.items():
                got = pushed[li].get(d)
                if got is None:
                    src = self.master[li][_idx_key(idx)]
                    if np.issubdtype(src.dtype, np.floating):
                        # copy (astype) even at equal dtype: device_put may
                        # zero-copy an aligned host buffer and the master
                        # is mutated in place on later steps
                        src = src.astype(self.push_dtype or np.float32)
                    got = (jax.device_put(src, d), time.perf_counter())
                    stats.h2d_bytes += src.nbytes
                arr, t_issue = got
                first_issue = t_issue if first_issue is None \
                    else min(first_issue, t_issue)
                arrs.append(arr)
            out.append(jax.make_array_from_single_device_arrays(
                shape, sh, arrs))
        t0 = time.perf_counter()
        jax.block_until_ready(out)  # dslint: allow(host-sync-in-step-path) sanctioned offload seam: books the exposed H2D tail
        t1 = time.perf_counter()
        stats.stall_s += t1 - t0
        if first_issue is not None:
            stats.add_span("h2d", first_issue, t1)
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def offload_summary(self) -> Dict[str, Any]:
        """Run-cumulative transfer/compute ledger + derived effective
        bandwidths — the bench rung's per-arm evidence."""
        t = dict(self.totals)
        out: Dict[str, Any] = {k: v for k, v in t.items()}
        for direction, secs in (("d2h", t.get("d2h_s", 0.0)),
                                ("h2d", t.get("h2d_s", 0.0)),
                                ("nvme_read", t.get("nvme_read_s", 0.0))):
            nbytes = t.get(f"{direction}_bytes", 0)
            out[f"{direction}_gbps"] = (
                nbytes / 1e9 / secs if secs > 0 else None)
        out["overlap_efficiency"] = overlap_efficiency(
            t.get("stall_s", 0.0), t.get("transfer_s", 0.0))
        if self._window is not None:
            out["window_hwm_bytes"] = self._window.hwm_bytes
            out["window_bound_bytes"] = self._window.bound_bytes
        return out

    # ---------------------------------------------------------------- helpers
    def _allreduce_host(self, sq: float, finite: bool
                        ) -> Tuple[float, bool]:
        if jax.process_count() == 1:
            return sq, finite
        from jax.experimental import multihost_utils

        vals = multihost_utils.process_allgather(
            np.asarray([sq, 1.0 if finite else 0.0], np.float64))
        return float(vals[:, 0].sum()), bool(vals[:, 1].min() > 0.5)

    def _assemble(self, store) -> Any:
        """Per-host shards → global arrays in the shard layout (cheap —
        local device_puts only; replicas reuse their index's shard)."""
        sh_leaves = jax.tree_util.tree_leaves(self.shard_shardings)
        out = []
        for shards, sh, dmap, shape in zip(store, sh_leaves,
                                           self._dev_index, self._shapes):
            arrs = [jax.device_put(shards[_idx_key(idx)], d)
                    for d, idx in dmap.items()]
            out.append(jax.make_array_from_single_device_arrays(
                shape, sh, arrs))
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def master_global_tree(self) -> Any:
        """The fp32 master as GLOBAL arrays in the shard layout (used for
        the param push-back after restore and multi-controller
        checkpointing via orbax)."""
        return self._assemble(self.master)

    def moments_global_tree(self) -> Dict[str, Any]:
        """Adam moments as global arrays (checkpoint payload)."""
        return {"m": self._assemble(self._moment_store("m")),
                "v": self._assemble(self._moment_store("v")),
                "step": np.asarray(self.step_count, np.int32)}

    # ------------------------------------------- single-controller full view
    def full_leaf_value(self, li: int, store: Optional[list] = None
                        ) -> np.ndarray:
        """The COMPLETE value of leaf ``li`` assembled from local shards —
        only meaningful when this host addresses every shard (single
        controller); callers guard on ``jax.process_count() == 1``."""
        shards = (store or self.master)[li]
        shape = self._shapes[li]
        example = next(iter(shards.values()))
        out = np.zeros(shape, example.dtype)
        for idx in self._dev_index[li].values():
            out[idx] = shards[_idx_key(idx)]
        return out

    def set_leaf_value(self, li: int, value: np.ndarray) -> None:
        """Write a full leaf value back into the master shards (the
        single-controller debug/introspection path — tensor_fragment)."""
        shards = self.master[li]
        for idx in self._dev_index[li].values():
            k = _idx_key(idx)
            shards[k] = np.array(value[idx], dtype=shards[k].dtype)

    def full_moment_value(self, li: int, which: str) -> np.ndarray:
        """Full value of one moment leaf (reads through the NVMe store
        without disturbing it — a retrieve consumes the read, not the
        file)."""
        store = self.m if which == "m" else self.v
        if self.swapper is None:
            return self.full_leaf_value(li, store)
        shards = {}
        for k in sorted(self._swap_keys[li]):
            self.swapper.prefetch(f"{which}/{li}/{k}")
        for k in self._swap_keys[li]:
            shards[k] = self.swapper.retrieve(f"{which}/{li}/{k}")
        for k, a in store[li].items():
            if k not in shards:
                shards[k] = a
        view = list(store)
        view[li] = shards
        return self.full_leaf_value(li, view)

    def load_state(self, master_tree: Any, moments: Optional[Dict[str, Any]]
                   ) -> None:
        """Restore from global arrays (resharding handled by the caller's
        checkpoint engine restoring into ``shard_shardings``)."""
        def pull(tree, store):
            for i, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
                shards: Dict[str, np.ndarray] = {}
                for s in leaf.addressable_shards:
                    k = _idx_key(s.index)
                    if k not in shards:
                        a = np.array(s.data)   # writable copy
                        if np.issubdtype(a.dtype, np.floating):
                            a = a.astype(np.float32)
                        shards[k] = a          # ints keep their dtype
                store[i] = shards

        pull(master_tree, self.master)
        if moments is not None:
            pull(moments["m"], self.m)
            pull(moments["v"], self.v)
            self.step_count = int(np.asarray(moments["step"]))
            if self.swapper is not None:
                self._offload_moments()  # restored moments back to NVMe

class _Done:
    """Completed-future shim for ``overlap=False`` (identical math, inline
    execution — the bit-parity reference arm)."""

    __slots__ = ("_value",)

    def __init__(self, value):
        self._value = value

    def result(self):
        return self._value
