"""Optimizer construction.

Analog of the reference's optimizer layer:

* ``engine._configure_basic_optimizer`` (``runtime/engine.py:1267``) — name → impl
  selection (Adam/AdamW/FusedAdam/CPUAdam/Lamb/FusedLamb/Lion/OneBitAdam/…).
* Native fused kernels ``csrc/adam/multi_tensor_adam.cu``, ``csrc/lamb/``,
  ``csrc/lion/`` (multi-tensor-apply loops).

TPU shift: a jitted ``optax`` update over the whole param pytree IS the fused
multi-tensor kernel — XLA fuses the elementwise chain across arrays; no custom kernel
is warranted (SURVEY.md §2.5 FusedAdam row). ``inject_hyperparams`` exposes the live
LR in optimizer state for monitors, like the reference reads ``param_groups[0]['lr']``.
"""
from typing import Any, Callable, Dict, Optional

import optax

from ..utils.logging import logger

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM = "fusedadam"
CPU_ADAM = "cpuadam"  # host-offloaded step: same math, placed on host backend
LAMB_OPTIMIZER = "lamb"
FUSED_LAMB = "fusedlamb"
LION_OPTIMIZER = "lion"
FUSED_LION = "fusedlion"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"
ONEBIT_ADAM = "onebitadam"
ZERO_ONE_ADAM = "zerooneadam"
ONEBIT_LAMB = "onebitlamb"


def _common(params: Dict[str, Any]):
    lr = float(params.get("lr", 1e-3))
    betas = params.get("betas", (0.9, 0.999))
    eps = float(params.get("eps", 1e-8))
    wd = float(params.get("weight_decay", 0.0))
    return lr, (float(betas[0]), float(betas[1])), eps, wd


def _path_segments(path):
    """Pytree key path → name segments, covering all four key types
    (DictKey.key, SequenceKey.idx, GetAttrKey.name, FlattenedIndexKey.key)."""
    segs = []
    for k in path:
        for attr in ("key", "name", "idx"):
            if hasattr(k, attr):
                segs.append(str(getattr(k, attr)))
                break
        else:
            segs.append(str(k))
    return segs


def _decay_mask(patterns):
    """Weight-decay mask from path patterns (the torch param-group idiom —
    `{"params": no_decay, "weight_decay": 0.0}` for biases/norms — as a
    config knob: ``optimizer.params.no_decay_patterns``). A pattern matches
    a WHOLE path segment (glob syntax allowed: "b", "bias", "*_norm"), or —
    when it contains "/" — a substring of the "/"-joined path. Bare
    substring matching is deliberately NOT used: a short pattern like "b"
    must not silently un-decay "embed" or "blocks". Returns a callable
    params-tree → bool tree (True = decay) for optax's ``mask=``, or None
    when unset."""
    if not patterns:
        return None
    import fnmatch

    pats = [str(x) for x in patterns]

    def excluded(segs):
        joined = "/".join(segs)
        for pat in pats:
            if "/" in pat:
                if pat in joined:
                    return True
            elif any(fnmatch.fnmatch(seg, pat) for seg in segs):
                return True
        return False

    def mask(tree):
        import jax

        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: not excluded(_path_segments(path)), tree)

    return mask


def build_optimizer(opt_type: str, params: Dict[str, Any],
                    lr_schedule: Optional[Callable] = None) -> optax.GradientTransformation:
    """Map config ``optimizer.type``+``params`` to an optax transform.

    1-bit Adam (reference ``runtime/fp16/onebit/adam.py``) maps to the native
    transform in ``runtime/onebit.py`` (frozen-variance + error-feedback
    sign-compressed momentum); the wire-compressed collective itself lives in
    ``comm/quantized.py`` for shard_map DP loops — under plain GSPMD the
    gradient mean is fused into the backward pass, so compression applies to
    the momentum operator instead.
    """
    t = opt_type.lower().replace("_", "")
    lr, betas, eps, wd = _common(params)
    schedule = lr_schedule if lr_schedule is not None else lr
    mask = _decay_mask(params.get("no_decay_patterns"))
    wd_kw = {} if mask is None else {"mask": mask}
    if mask is not None and t in (ONEBIT_ADAM, ZERO_ONE_ADAM, ONEBIT_LAMB):
        # the 1-bit family applies decay inside its fused update; silently
        # decaying excluded params would diverge from the same config under
        # AdamW — refuse loudly instead
        raise ValueError(
            f"no_decay_patterns is not supported with {opt_type!r} "
            f"(the 1-bit optimizers decay every leaf); drop the patterns "
            f"or use AdamW/Lamb/Lion")

    if t == ONEBIT_ADAM:
        from .onebit import onebit_adam

        # static_args: only the LR is a traced hyperparam — the rest gate
        # python control flow in the factory and must stay concrete under jit
        return optax.inject_hyperparams(
            onebit_adam,
            static_args=("b1", "b2", "eps", "freeze_step", "weight_decay"))(
            learning_rate=schedule, b1=betas[0], b2=betas[1], eps=eps,
            freeze_step=int(params.get("freeze_step", 100)), weight_decay=wd)
    if t == ZERO_ONE_ADAM:
        from .onebit import zero_one_adam

        return zero_one_adam(
            schedule, b1=betas[0], b2=betas[1], eps=eps,
            var_freeze_step=int(params.get("var_freeze_step", 100000)),
            var_update_scaler=int(params.get("var_update_scaler", 16)),
            local_step_scaler=int(params.get("local_step_scaler", 32678)),
            local_step_clipper=int(params.get("local_step_clipper", 16)),
            weight_decay=wd)
    if t == ONEBIT_LAMB:
        from .onebit import onebit_lamb

        return onebit_lamb(
            schedule, b1=betas[0], b2=betas[1], eps=eps,
            freeze_step=int(params.get("freeze_step", 100)),
            weight_decay=wd,
            max_coeff=float(params.get("max_coeff", 10.0)),
            min_coeff=float(params.get("min_coeff", 0.01)),
            coeff_beta=float(params.get("coeff_beta", 0.9)),
            factor_max=float(params.get("factor_max", 4.0)),
            factor_min=float(params.get("factor_min", 0.5)),
            factor_threshold=float(params.get("factor_threshold", 0.1)))

    if t == ADAMW_OPTIMIZER:
        tx = optax.inject_hyperparams(optax.adamw, static_args=("mask",))(
            learning_rate=schedule, b1=betas[0], b2=betas[1], eps=eps,
            weight_decay=wd, **wd_kw)
    elif t in (ADAM_OPTIMIZER, FUSED_ADAM, CPU_ADAM):
        # reference FusedAdam/CPUAdam default adam_w_mode=True → AdamW;
        # adam_w_mode=False is classic Adam (no decoupled decay)
        if params.get("adam_w_mode", params.get("adamw_mode", True)):
            tx = optax.inject_hyperparams(optax.adamw,
                                          static_args=("mask",))(
                learning_rate=schedule, b1=betas[0], b2=betas[1], eps=eps,
                weight_decay=wd, **wd_kw)
        else:
            tx = optax.inject_hyperparams(optax.adam)(
                learning_rate=schedule, b1=betas[0], b2=betas[1], eps=eps)
    elif t in (LAMB_OPTIMIZER, FUSED_LAMB):
        tx = optax.inject_hyperparams(optax.lamb, static_args=("mask",))(
            learning_rate=schedule, b1=betas[0], b2=betas[1], eps=eps,
            weight_decay=wd, **wd_kw)
    elif t in (LION_OPTIMIZER, FUSED_LION):
        tx = optax.inject_hyperparams(optax.lion, static_args=("mask",))(
            learning_rate=schedule, b1=betas[0], b2=betas[1],
            weight_decay=wd, **wd_kw)
    elif t == SGD_OPTIMIZER:
        tx = optax.inject_hyperparams(optax.sgd)(
            learning_rate=schedule, momentum=float(params.get("momentum", 0.0)))
    elif t == ADAGRAD_OPTIMIZER:
        tx = optax.inject_hyperparams(optax.adagrad)(learning_rate=schedule, eps=eps)
    else:
        raise ValueError(f"unknown optimizer type {opt_type!r}")
    return tx


def current_lr(opt_state) -> Any:
    """Pull the live learning rate out of an inject_hyperparams state (reference:
    ``engine.get_lr``)."""
    try:
        return opt_state.hyperparams["learning_rate"]
    except (AttributeError, KeyError, TypeError):
        for leaf in (opt_state if isinstance(opt_state, tuple) else [opt_state]):
            hp = getattr(leaf, "hyperparams", None)
            if hp and "learning_rate" in hp:
                return hp["learning_rate"]
    return None
