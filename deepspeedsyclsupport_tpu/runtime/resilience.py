"""Preemption-aware resilience: signal → emergency save → distinguished exit.

TPU VMs are maintenance-evicted and spot-preempted with a SIGTERM and a short
grace window (the failure domain of arXiv 2011.03641). Losing the window means
losing every step since the last periodic checkpoint, so:

* :class:`ResilienceManager` installs SIGTERM/SIGINT handlers that only *set a
  flag* — the handler itself must stay async-signal-safe and must never
  interrupt a jitted step mid-flight.
* The engine polls :meth:`at_step_boundary` after every ``train_batch``; on a
  pending preemption it performs an emergency ``save_checkpoint``, waits for
  durability, flushes monitors, and exits with :data:`PREEMPTION_EXIT_CODE`.
* The elastic agent (``elasticity/elastic_agent.py``) recognizes that exit
  code as a *clean* preemption: the restart is free (not counted against
  ``restart_limit``) because the worker left a durable checkpoint behind.

Simulated preemptions (``utils/fault_injection.py`` ``preempt_at_step``) enter
through the same ``at_step_boundary`` path, so tests exercise the identical
save-and-exit machinery without process-level signals.

Numerical faults are the sibling fault class: ``runtime/sentinel.py`` owns
NaN/loss-spike detection and the skip → rollback → abort ladder, exiting
with :data:`DIVERGENCE_EXIT_CODE` (220, re-exported here) when the ladder is
exhausted. Its injectors (``nan_step``/``loss_spike``/``bad_batch``,
``utils/fault_injection.py corrupt_batch``) poison batches in the same
rank/step-targeted style ``preempt_at_step`` uses for this module.
"""
import signal
import sys
import threading
from typing import Any, Callable, Iterable, Optional

from ..utils.fault_injection import get_fault_injector
from ..utils.logging import logger
from .sentinel import DIVERGENCE_EXIT_CODE  # noqa: F401  (re-export)

# Distinguished "I was preempted and saved cleanly" exit code. Chosen outside
# the shell's 126/127/128+N signal-death range so it can't be confused with a
# crash, and mirrored by the elastic agent's free-restart accounting.
PREEMPTION_EXIT_CODE = 217


class ResilienceManager:
    """Owns the signal → flag → emergency-save → exit pipeline for one engine.

    ``exit_fn`` is injectable (default ``sys.exit``) so tests can observe the
    exit without killing the pytest process."""

    def __init__(self, engine: Any, save_dir: str,
                 exit_code: int = PREEMPTION_EXIT_CODE,
                 exit_fn: Optional[Callable[[int], None]] = None):
        self.engine = engine
        self.save_dir = save_dir
        self.exit_code = exit_code
        self._exit_fn = exit_fn or sys.exit
        self.preemption_requested = threading.Event()
        # signal-handler side: a plain attribute store is the only operation
        # guaranteed not to deadlock when the handler interrupts the main
        # thread mid-lock (Event.set, logging and the resilience counters all
        # take non-reentrant locks the interrupted frame may already hold)
        self._signal_pending = False
        self._signal_num: Optional[int] = None
        self._prev_handlers = {}

    # ------------------------------------------------------------- signals
    def install(self, signals: Iterable[int] = (signal.SIGTERM,
                                                signal.SIGINT)) -> None:
        """Install handlers (main thread only — a CPython constraint)."""
        for s in signals:
            self._prev_handlers[s] = signal.signal(s, self._on_signal)

    def uninstall(self) -> None:
        while self._prev_handlers:
            s, prev = self._prev_handlers.popitem()
            signal.signal(s, prev)

    def _on_signal(self, signum, frame) -> None:
        # attribute stores ONLY: the handler runs on the main thread between
        # bytecodes, so taking any lock (Event, logging, counters) can
        # deadlock against the frame it interrupted — e.g. a SIGTERM landing
        # inside retry_io's counter increment during the very checkpoint
        # write preemptions tend to coincide with. Everything else (log,
        # counter, emergency save) happens at the next step boundary.
        self._signal_num = signum
        self._signal_pending = True

    def request_preemption(self) -> None:
        if not self.preemption_requested.is_set():
            self.preemption_requested.set()
            from ..monitor.monitor import resilience_counters

            resilience_counters.incr("preemptions")

    # -------------------------------------------------------- step boundary
    def at_step_boundary(self) -> None:
        """Called by the engine after each completed optimizer step."""
        if self._signal_pending:
            self._signal_pending = False
            logger.warning("received signal %s: emergency checkpoint at "
                           "step boundary", self._signal_num)
            self.request_preemption()
        if not self.preemption_requested.is_set():
            if get_fault_injector().should_preempt(self.engine.global_steps):
                logger.warning("fault injection: simulated preemption at "
                               "step %d", self.engine.global_steps)
                self.request_preemption()
            else:
                return
        self._emergency_save_and_exit()

    def _emergency_save_and_exit(self) -> None:
        from ..monitor.monitor import resilience_counters

        path = self.engine.save_checkpoint(self.save_dir)
        self.engine.checkpoint_engine.commit()  # durable before we die
        resilience_counters.incr("emergency_saves")
        try:
            self.engine._flush_monitor()
            self.engine.monitor.flush()
        except Exception as e:  # monitoring never blocks the exit
            logger.warning("monitor flush during preemption failed: %s", e)
        telemetry = getattr(self.engine, "telemetry", None)
        if telemetry is not None:
            # force the flight-recorder ring onto disk: the last steps
            # before this death must be inspectable after the fact
            try:
                telemetry.dump("preemption")
            except Exception as e:
                logger.warning("flight-recorder dump during preemption "
                               "failed: %s", e)
        logger.warning("emergency checkpoint %s durable; exiting with "
                       "preemption code %d", path, self.exit_code)
        self.uninstall()
        self._exit_fn(self.exit_code)
