from .config import DSTpuConfig
from .engine import Engine, initialize
