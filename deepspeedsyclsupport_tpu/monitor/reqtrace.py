"""Request-time attribution: stage registry, journal join, TTFT/ITL waterfall.

The serving-side sibling of the MFU ledger (``monitor/mfu.py``):
``Serve/ttft_s`` p95 says a request was slow, not whether edge admission,
router queueing, replica spool transport, chunked prefill, fused-decode
rounds, preemption/requeue or failover replay ate the budget. This module
owns the three pieces that answer it:

* **stage registry** — :data:`SERVE_STAGES` / :data:`FLEET_STAGES`, the
  canonical lifecycle-stage names. ``ServingSession``/``RequestJournal``
  stamp ``serve/stage`` records and ``FleetRouter`` stamps ``fleet/stage``
  records with these literals riding the EXISTING journal / flight-recorder
  streams (no second transport); ``monitor/telemetry.py`` enumerates the
  strict ``Serve/stage.*`` / ``Fleet/stage.*`` event families from these
  tuples, and dslint's ``undeclared-stage-name`` rule rejects any literal
  outside them (the ``undeclared-region`` pattern).
* **join** — :func:`join_traces` fuses the router stream + per-replica
  journals (uid-keyed, wall-``t`` ordered, torn-tail salvaged) into
  per-request span trees that survive generation respawns and failover:
  a replayed request's trace spans the dead replica's segment and the
  survivor's replay segment. Stage self-times are a telescoping partition
  of the request's timeline, so the reconciliation contract holds by
  construction: stage sums match the journal-observed enqueue→close wall
  time within 5%, residual reported as ``unattributed``.
* **attribution** — :func:`attribution`: TTFT and ITL decomposed per stage
  at p50/p95/p99, tail attribution (which stage grew for the slowest
  decile vs the median cohort), SLO burn over sliding windows, and the
  N worst requests' waterfalls — the ``detail.request_waterfall`` payload
  the bench rungs emit and ``tools/trace_report.py --requests`` renders.

DELIBERATELY STDLIB-ONLY: ``tools/trace_report.py`` loads this file by path
on jax-less login nodes (the ``pod.py``/``mfu.py`` contract —
telemetry/serving import FROM here, never the reverse).
"""
import glob as _glob
import json
import math
import os
import re
from typing import (Any, Dict, Iterable, List, Optional, Sequence, Tuple)

#: Canonical replica-side lifecycle stages. The first block are STAMPED —
#: ``ServingSession``/``serve_worker`` write ``serve/stage`` records with
#: these literals (dslint's ``undeclared-stage-name`` rule rejects any
#: other). The rest are DERIVED by the join from the emit/close stream:
#: ``decode`` from inter-emit gaps, ``finalize`` (last emit → close),
#: ``unattributed`` (any interval the classifier cannot name — the
#: reconciliation residual).
STAMPED_SERVE_STAGES = ("gate", "queue_wait", "requeue_wait", "prefill",
                        "prefill_chunk", "decode_round", "preempt",
                        "replay", "spool_wait")
DERIVED_SERVE_STAGES = ("decode", "finalize", "unattributed")
SERVE_STAGES = STAMPED_SERVE_STAGES + DERIVED_SERVE_STAGES

#: Router-side stages (``fleet/stage`` records). ``transport`` is derived:
#: the route→replica-admit gap (spool wait + process hop for
#: ``ProcessReplica``; ~0 in-process).
STAMPED_FLEET_STAGES = ("edge_gate", "placement", "failover_claim",
                        "replay_segment")
DERIVED_FLEET_STAGES = ("transport",)
FLEET_STAGES = STAMPED_FLEET_STAGES + DERIVED_FLEET_STAGES

#: Stages whose per-request self-time the session observes into
#: ``Serve/stage.<name>_s`` histograms at close (queue wait has its own
#: satellite family, ``Serve/queue_wait_s``).
STAGE_HISTOGRAMS = ("prefill", "decode")

_SERVE_STAGE_SET = frozenset(SERVE_STAGES)
_FLEET_STAGE_SET = frozenset(FLEET_STAGES)


def check_stage(name: str, fleet: bool = False) -> str:
    """Validate a stage literal against the registry — the runtime twin of
    dslint's ``undeclared-stage-name`` rule (``mfu.region_scope`` pattern:
    a typo'd stage must fail loudly, not silently orphan its time)."""
    ok = name in (_FLEET_STAGE_SET if fleet else _SERVE_STAGE_SET)
    if not ok:
        kind = "fleet" if fleet else "serve"
        declared = FLEET_STAGES if fleet else SERVE_STAGES
        raise ValueError(f"undeclared {kind} stage {name!r}; declared: "
                         f"{declared} (monitor/reqtrace.py)")
    return name


# =========================================================================
# Stream loading (torn-tail salvage; the load_journal contract)
# =========================================================================


def load_stream(path: str) -> List[Dict[str, Any]]:
    """Parse one JSONL stream; a torn final line (crash mid-write) is
    skipped, not fatal — everything before it was flushed durably."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return []
    out: List[Dict[str, Any]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn tail
        if isinstance(rec, dict):
            out.append(rec)
    return out


_ATT_RE = re.compile(r"\.att([0-9.]+)\.jsonl$")


def file_attempt(path: str) -> str:
    """Generation/attempt suffix from a journal filename
    (``journal_rank0.att1.0.jsonl`` → ``"1.0"``; ``DSTPU_FLEET_GEN``
    namespaces the attempt — ``supervisor.journal_path``)."""
    m = _ATT_RE.search(os.path.basename(path))
    return m.group(1) if m else ""


def discover_root(root: str) -> Tuple[Dict[str, List[str]], List[str]]:
    """Fleet-root layout discovery: ``{replica_id: [journal files]}``
    (oldest incarnation first) plus the router stream files. Accepts a
    fleet root (``replica<id>/journal/``), a bare journal dir, or a dir
    of journals + ``router*.jsonl`` side by side."""
    replicas: Dict[str, List[str]] = {}
    if os.path.isdir(root):
        for sub in sorted(os.listdir(root)):
            jdir = os.path.join(root, sub, "journal")
            if sub.startswith("replica") and os.path.isdir(jdir):
                files = sorted(
                    _glob.glob(os.path.join(jdir, "journal_rank*.jsonl")),
                    key=lambda p: (os.path.getmtime(p), p))
                if files:
                    replicas[sub[len("replica"):]] = files
        if not replicas:
            files = sorted(
                _glob.glob(os.path.join(root, "journal_rank*.jsonl")),
                key=lambda p: (os.path.getmtime(p), p))
            if files:
                replicas["0"] = files
    router = sorted(_glob.glob(os.path.join(root, "router*.jsonl"))
                    ) if os.path.isdir(root) else []
    return replicas, router


# =========================================================================
# Join: streams → per-request span trees
# =========================================================================

#: Interval classifier: (previous edge, next edge) → stage. Every named
#: interval is a consecutive slice of the request's timeline, so the
#: per-stage self-times telescope to enqueue→close exactly — the 5%
#: reconciliation contract holds unless records are missing (torn tail),
#: and THAT shortfall is what ``unattributed`` reports.
_INTERVAL_STAGE = {
    ("route", "admit"): "transport",
    ("admit", "activate"): "queue_wait",
    ("admit", "emit"): "prefill",       # activation record lost (torn tail)
    ("admit", "close"): "queue_wait",   # closed while queued (shed/timeout)
    ("admit", "admit"): "replay",       # died before activation, replayed
    ("admit", "preempt"): "queue_wait",
    ("activate", "emit"): "prefill",
    ("activate", "close"): "prefill",
    ("activate", "preempt"): "prefill",
    ("activate", "admit"): "replay",
    ("emit", "emit"): "decode",
    ("emit", "preempt"): "decode",
    ("emit", "close"): "finalize",
    ("emit", "admit"): "replay",        # dead-replica gap → survivor admit
    ("preempt", "activate"): "requeue_wait",
    ("preempt", "close"): "requeue_wait",
    ("preempt", "admit"): "replay",
}


def _new_trace(uid: int) -> Dict[str, Any]:
    return {"uid": uid, "segments": [], "intervals": [], "stages": {},
            "t_route": None, "t_admit": None, "t_first_emit": None,
            "t_close": None, "ttft_s": None, "wall_s": None,
            "unattributed_s": 0.0, "reconciled_frac": None,
            "tokens": 0, "closes": 0, "close_reason": "", "outcome": "",
            "cached_prefix_len": None, "spool_wait_s": 0.0,
            "rounds": {"fused": 0, "per_token": 0},
            "ttft_sla_s": None, "tenant": "", "verdicts": [],
            "replays": 0, "replica_path": []}


def join_traces(streams: Iterable[Tuple[str, str, Sequence[Dict[str, Any]]]],
                router_records: Sequence[Dict[str, Any]] = (),
                since: Optional[float] = None) -> Dict[int, Dict[str, Any]]:
    """Fuse router stream + per-replica journal streams into per-request
    span trees.

    ``streams`` is ``[(replica_id, attempt, records), ...]`` — what
    :func:`join_root` builds from disk, or what a caller hands over from
    in-memory ``trace_log`` buffers (``ServingSession.trace_log`` /
    ``FleetRouter.trace_log``). Records are ordered by wall ``t`` (the one
    clock every stream stamps) with append order breaking ties, so a
    replayed request's trace spans replicas and generations. ``since``
    drops requests whose first record predates it (per-load-point joins
    over an accumulating journal dir).
    """
    # (t, idx, kind, payload) per uid; router records first so same-t route
    # edges sort ahead of the replica admit they caused
    events: Dict[int, List[Tuple[float, int, str, Dict[str, Any]]]] = {}
    idx = 0

    def _push(uid: int, t: float, kind: str, payload: Dict[str, Any]) -> None:
        nonlocal idx
        if int(uid) < 0:
            # batch-scope stamps (decode_round fanout carriers, the
            # router's fleet-wide failover_claim) are not requests
            return
        idx += 1
        events.setdefault(int(uid), []).append((float(t), idx, kind, payload))

    for rec in router_records:
        name = rec.get("name")
        data = rec.get("data") or {}
        t = float(rec.get("t", 0.0))
        uid = data.get("uid")
        if uid is None:
            continue
        if name == "fleet/route":
            _push(uid, t, "route", {"replica": data.get("replica", "")})
        elif name == "fleet/shed":
            _push(uid, t, "edge_shed", {"reason": data.get("reason", "")})
        elif name == "fleet/stage":
            _push(uid, t, "fleet_stage", dict(data))
        elif name == "fleet/failover":
            _push(uid, t, "failover", dict(data))
    for replica_id, attempt, records in streams:
        for rec in records:
            name = rec.get("name")
            data = rec.get("data") or {}
            t = float(rec.get("t", 0.0))
            uid = data.get("uid")
            if uid is None:
                continue
            if name == "serve/admit":
                n_prompt = data.get("n_tokens",
                                    len(data.get("tokens", []) or []))
                _push(uid, t, "admit", {
                    "replica": replica_id, "attempt": attempt,
                    "replayed": bool(data.get("replayed")),
                    "out_n": data.get("watermark",
                                      len(data.get("out", []) or [])),
                    "tenant": data.get("tenant", ""),
                    "ttft_sla_s": data.get("ttft_sla_s"),
                    "n_prompt": int(n_prompt)})
            elif name == "serve/emit":
                _push(uid, t, "emit",
                      {"n": int(data.get("n",
                                         len(data.get("tokens", []) or [])))})
            elif name == "serve/close":
                _push(uid, t, "close", {"reason": data.get("reason", "")})
            elif name == "serve/stage":
                stage = data.get("stage", "")
                if stage == "decode_round":
                    for u in data.get("uids", ()):
                        _push(u, t, "round",
                              {"mode": data.get("mode", "per_token")})
                elif stage in ("queue_wait", "requeue_wait"):
                    _push(uid, t, "activate", dict(data))
                elif stage == "preempt":
                    _push(uid, t, "preempt", dict(data))
                else:
                    _push(uid, t, "stage", dict(data))

    traces: Dict[int, Dict[str, Any]] = {}
    for uid, evs in events.items():
        evs.sort(key=lambda e: (e[0], e[1]))
        if since is not None and evs[0][0] < since:
            continue
        tr = _new_trace(uid)
        prev: Optional[Tuple[float, str]] = None  # last EDGE (t, kind)
        seg: Optional[Dict[str, Any]] = None
        for t, _i, kind, payload in evs:
            if kind == "round":
                key = ("fused" if payload.get("mode") == "fused"
                       else "per_token")
                tr["rounds"][key] += 1
                continue
            if kind == "stage":
                stage = payload.get("stage", "")
                if stage == "spool_wait":
                    tr["spool_wait_s"] += float(payload.get("dur", 0.0))
                elif stage == "gate":
                    tr["verdicts"].append(payload.get("verdict", ""))
                elif stage == "prefill":
                    if payload.get("cached_prefix_len") is not None:
                        tr["cached_prefix_len"] = int(
                            payload["cached_prefix_len"])
                continue
            if kind == "fleet_stage":
                stage = payload.get("stage", "")
                if stage == "placement":
                    tr["verdicts"].append("routed")
                elif stage == "edge_gate":
                    tr["verdicts"].append(payload.get("verdict", ""))
                continue
            if kind == "failover":
                if payload.get("outcome") in ("replayed", "dispatched"):
                    tr["replays"] += 1
                continue
            if kind == "edge_shed":
                tr["outcome"] = "edge_shed"
                tr["close_reason"] = f"edge_shed:{payload.get('reason', '')}"
                continue
            # ---- timeline edges -------------------------------------
            if kind == "route":
                # metadata edge: seeds t_route / the replica path and, at
                # stream start, the transport interval. A route stamp can
                # land AFTER the replica's admit (an in-process submit
                # returns before the router records the route) — it must
                # not reset ``prev`` mid-chain or the admit→activate→emit
                # intervals it would interrupt become unattributed.
                tr["t_route"] = t if tr["t_route"] is None else tr["t_route"]
                rep = payload.get("replica", "")
                if rep and (not tr["replica_path"]
                            or tr["replica_path"][-1] != rep):
                    tr["replica_path"].append(rep)
                if prev is None:
                    prev = (t, kind)
                continue
            if prev is not None:
                dt = max(0.0, t - prev[0])
                stage = _INTERVAL_STAGE.get((prev[1], kind), "unattributed")
                if dt > 0:
                    tr["intervals"].append((stage, prev[0], t))
            if kind == "admit":
                if tr["t_admit"] is None:
                    tr["t_admit"] = t
                    tr["tenant"] = payload.get("tenant", "")
                    tr["ttft_sla_s"] = payload.get("ttft_sla_s")
                seg = {"replica": payload.get("replica", ""),
                       "attempt": payload.get("attempt", ""),
                       "replayed": payload.get("replayed", False),
                       "watermark": payload.get("out_n", 0),
                       "t_admit": t, "t_first_emit": None,
                       "t_last": t, "closed": False, "tokens": 0}
                tr["segments"].append(seg)
                if payload.get("replica") and (
                        not tr["replica_path"]
                        or tr["replica_path"][-1] != payload["replica"]):
                    tr["replica_path"].append(payload["replica"])
            elif kind == "activate":
                if seg is not None:
                    seg["t_last"] = t
                if payload.get("cached_prefix_len") is not None \
                        and tr["cached_prefix_len"] is None:
                    tr["cached_prefix_len"] = int(payload["cached_prefix_len"])
            elif kind == "emit":
                if tr["t_first_emit"] is None:
                    tr["t_first_emit"] = t
                tr["tokens"] += payload.get("n", 0)
                if seg is not None:
                    if seg["t_first_emit"] is None:
                        seg["t_first_emit"] = t
                    seg["t_last"] = t
                    seg["tokens"] += payload.get("n", 0)
            elif kind == "preempt":
                if seg is not None:
                    seg["t_last"] = t
            elif kind == "close":
                tr["closes"] += 1
                tr["t_close"] = t
                tr["close_reason"] = payload.get("reason", "")
                if seg is not None:
                    seg["closed"] = True
                    seg["t_last"] = t
            prev = (t, kind)
        # ---- derived summary ----------------------------------------
        if tr["t_admit"] is not None and tr["t_first_emit"] is not None \
                and tr["segments"] and not tr["segments"][0]["replayed"]:
            tr["ttft_s"] = tr["t_first_emit"] - tr["t_admit"]
        if tr["t_admit"] is not None and tr["t_close"] is not None:
            tr["wall_s"] = max(0.0, tr["t_close"] - tr["t_admit"])
            for stage, t0, t1 in tr["intervals"]:
                if t0 >= tr["t_admit"]:  # transport precedes enqueue
                    tr["stages"][stage] = (tr["stages"].get(stage, 0.0)
                                           + (t1 - t0))
            attributed = sum(v for s, v in tr["stages"].items()
                             if s != "unattributed")
            tr["unattributed_s"] = max(0.0, tr["wall_s"] - attributed)
            tr["reconciled_frac"] = (1.0 if tr["wall_s"] <= 0 else
                                     min(1.0, attributed / tr["wall_s"]))
        if not tr["outcome"]:
            reason = tr["close_reason"]
            tr["outcome"] = ("open" if tr["closes"] == 0 else
                             "shed" if reason.startswith("shed")
                             or reason == "replay_shed" else "closed")
        traces[uid] = tr
    return traces


def join_root(root: str, since: Optional[float] = None
              ) -> Dict[int, Dict[str, Any]]:
    """Disk entry point: discover + load + join a fleet root (or bare
    journal dir)."""
    replicas, router_files = discover_root(root)
    router_records: List[Dict[str, Any]] = []
    for path in router_files:
        router_records.extend(load_stream(path))
    streams = [(rid, file_attempt(path), load_stream(path))
               for rid, files in sorted(replicas.items()) for path in files]
    return join_traces(streams, router_records, since=since)


# =========================================================================
# Attribution: traces → TTFT/ITL waterfall, tail, SLO burn, exemplars
# =========================================================================


def _rank_quantile(vals: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank quantile (exact, no interpolation — these are offline
    joins over full populations, not streaming buckets)."""
    if not vals:
        return None
    s = sorted(vals)
    return s[min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))]


def _quantiles(vals: Sequence[float]) -> Dict[str, Optional[float]]:
    return {f"p{int(q * 100)}": _rank_quantile(vals, q)
            for q in (0.5, 0.95, 0.99)}


def _clip_stages(tr: Dict[str, Any], t0: float, t1: float
                 ) -> Dict[str, float]:
    """Per-stage seconds inside the window [t0, t1] (interval clipping)."""
    out: Dict[str, float] = {}
    for stage, a, b in tr["intervals"]:
        lo, hi = max(a, t0), min(b, t1)
        if hi > lo:
            out[stage] = out.get(stage, 0.0) + (hi - lo)
    return out


def slo_burn_windows(traces: Dict[int, Dict[str, Any]],
                     window_s: float = 60.0, budget: float = 0.05
                     ) -> List[Dict[str, Any]]:
    """TTFT-SLO burn rate over fixed sliding windows: per window the
    fraction of first tokens that missed their per-request SLA, divided by
    the error budget (burn > 1 ⇒ the budget is being spent faster than it
    accrues — the standard multi-window burn-rate alerting input)."""
    samples = [(tr["t_first_emit"],
                tr["ttft_s"] is not None and tr["ttft_sla_s"] is not None
                and tr["ttft_s"] <= tr["ttft_sla_s"])
               for tr in traces.values()
               if tr["t_first_emit"] is not None
               and tr["ttft_sla_s"] is not None and tr["ttft_s"] is not None]
    if not samples:
        return []
    samples.sort()
    t_lo, t_hi = samples[0][0], samples[-1][0]
    out: List[Dict[str, Any]] = []
    t = t_lo
    while t <= t_hi:
        inside = [ok for ts, ok in samples if t <= ts < t + window_s]
        if inside:
            miss = 1.0 - sum(inside) / len(inside)
            out.append({"t0": t, "n": len(inside),
                        "miss_frac": round(miss, 4),
                        "burn": round(miss / max(budget, 1e-9), 3)})
        t += window_s
    return out


def attribution(traces: Dict[int, Dict[str, Any]], worst_n: int = 5,
                slo_window_s: float = 60.0, slo_budget: float = 0.05
                ) -> Dict[str, Any]:
    """The request waterfall: stage-decomposed TTFT/ITL quantiles, tail
    attribution, reconciliation summary, SLO burn and worst-request
    exemplars — the ``detail.request_waterfall`` payload."""
    done = [tr for tr in traces.values()
            if tr["t_admit"] is not None and tr["t_close"] is not None]
    firsts = [tr for tr in done if tr["ttft_s"] is not None]
    out: Dict[str, Any] = {
        "requests": len(traces), "closed": len(done),
        "edge_sheds": sum(1 for tr in traces.values()
                          if tr["outcome"] == "edge_shed"),
        "multi_close": sum(1 for tr in traces.values() if tr["closes"] > 1),
        "failover_spans": sum(1 for tr in done if tr["replays"] > 0
                              or len({s["replica"]
                                      for s in tr["segments"]}) > 1),
    }
    recon = [tr["reconciled_frac"] for tr in done
             if tr["reconciled_frac"] is not None]
    out["reconciliation"] = {
        "median_frac": _rank_quantile(recon, 0.5),
        "min_frac": min(recon) if recon else None,
        "within_5pct_frac": (round(sum(1 for f in recon if f >= 0.95)
                                   / len(recon), 4) if recon else None)}
    # ---- TTFT decomposition --------------------------------------------
    stage_ttft: Dict[str, List[float]] = {}
    for tr in firsts:
        clipped = _clip_stages(tr, tr["t_admit"], tr["t_first_emit"])
        for stage in set(clipped) | set(stage_ttft):
            stage_ttft.setdefault(stage, []).append(clipped.get(stage, 0.0))
    # equal-length arrays (zeros for requests lacking a stage) so quantile
    # ranks align across stages
    n_first = len(firsts)
    for stage, vals in stage_ttft.items():
        vals.extend(0.0 for _ in range(n_first - len(vals)))
    out["ttft"] = _quantiles([tr["ttft_s"] for tr in firsts])
    out["ttft_by_stage"] = {
        stage: {**_quantiles(vals),
                "mean_s": round(sum(vals) / len(vals), 6) if vals else 0.0}
        for stage, vals in sorted(stage_ttft.items())}
    means = {s: v["mean_s"] for s, v in out["ttft_by_stage"].items()}
    out["dominant_ttft_stage"] = (max(means, key=means.get)
                                  if means else None)
    # ---- ITL decomposition (per emitted token past the first) ----------
    stage_itl: Dict[str, List[float]] = {}
    decoders = [tr for tr in done if tr["t_first_emit"] is not None
                and tr["tokens"] > 1]
    for tr in decoders:
        clipped = _clip_stages(tr, tr["t_first_emit"], tr["t_close"])
        denom = max(1, tr["tokens"] - 1)
        for stage in set(clipped) | set(stage_itl):
            stage_itl.setdefault(stage, []).append(
                clipped.get(stage, 0.0) / denom)
    n_dec = len(decoders)
    for stage, vals in stage_itl.items():
        vals.extend(0.0 for _ in range(n_dec - len(vals)))
    out["itl_by_stage"] = {
        stage: {**_quantiles(vals),
                "mean_s": round(sum(vals) / len(vals), 6) if vals else 0.0}
        for stage, vals in sorted(stage_itl.items())}
    # ---- tail attribution: slowest TTFT decile vs the median cohort ----
    if len(firsts) >= 4:
        ranked = sorted(firsts, key=lambda tr: tr["ttft_s"])
        n = len(ranked)
        tail = ranked[max(0, n - max(1, n // 10)):]
        mid = ranked[n // 4: max(n // 4 + 1, 3 * n // 4)]

        def _mean_stages(group):
            acc: Dict[str, float] = {}
            for tr in group:
                for stage, v in _clip_stages(
                        tr, tr["t_admit"], tr["t_first_emit"]).items():
                    acc[stage] = acc.get(stage, 0.0) + v
            return {s: v / len(group) for s, v in acc.items()}

        tail_m, mid_m = _mean_stages(tail), _mean_stages(mid)
        by_stage = {
            stage: {"median_s": round(mid_m.get(stage, 0.0), 6),
                    "tail_s": round(tail_m.get(stage, 0.0), 6),
                    "growth_s": round(tail_m.get(stage, 0.0)
                                      - mid_m.get(stage, 0.0), 6)}
            for stage in sorted(set(tail_m) | set(mid_m))}
        growth = {s: v["growth_s"] for s, v in by_stage.items()}
        out["tail"] = {
            "tail_n": len(tail), "median_n": len(mid),
            "by_stage": by_stage,
            "dominant_stage": (max(growth, key=growth.get)
                               if growth else None)}
    else:
        out["tail"] = None
    # ---- decode mode + prefix visibility -------------------------------
    out["decode_rounds"] = {
        "fused": sum(tr["rounds"]["fused"] for tr in done),
        "per_token": sum(tr["rounds"]["per_token"] for tr in done)}
    cached = [tr["cached_prefix_len"] for tr in done
              if tr["cached_prefix_len"] is not None]
    out["cached_prefix_tokens_mean"] = (
        round(sum(cached) / len(cached), 2) if cached else None)
    # ---- SLO burn ------------------------------------------------------
    burn = slo_burn_windows(traces, window_s=slo_window_s, budget=slo_budget)
    out["slo_burn"] = {
        "window_s": slo_window_s, "budget": slo_budget,
        "windows": burn,
        "max_burn": max((w["burn"] for w in burn), default=None)}
    # ---- worst-request exemplar waterfalls -----------------------------
    ranked = sorted(firsts, key=lambda tr: -(tr["ttft_s"] or 0.0))
    out["worst"] = [
        {"uid": tr["uid"], "ttft_s": round(tr["ttft_s"], 6),
         "wall_s": round(tr["wall_s"], 6) if tr["wall_s"] is not None
         else None,
         "tokens": tr["tokens"], "close_reason": tr["close_reason"],
         "replays": tr["replays"],
         "replica_path": tr["replica_path"],
         "cached_prefix_len": tr["cached_prefix_len"],
         "unattributed_s": round(tr["unattributed_s"], 6),
         "stages": {s: round(v, 6) for s, v in sorted(tr["stages"].items())}}
        for tr in ranked[:worst_n]]
    return out


def waterfall(streams: Iterable[Tuple[str, str, Sequence[Dict[str, Any]]]],
              router_records: Sequence[Dict[str, Any]] = (),
              since: Optional[float] = None, **kw) -> Dict[str, Any]:
    """join + attribution in one call (the bench rungs' per-load-point
    entry: hand over the in-memory ``trace_log`` buffers, get the
    ``detail.request_waterfall`` payload)."""
    return attribution(join_traces(streams, router_records, since=since),
                       **kw)
