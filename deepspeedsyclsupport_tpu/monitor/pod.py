"""Pod-scope observability: cross-rank trace fusion + comm/compute split.

PR 2's flight recorder is strictly rank-local (one JSONL per rank) and the
static collective census (``analysis/collectives.py``) is strictly
compile-time. This module is the layer between them: it fuses N rank-local
JSONL streams into one cluster timeline and answers the pod-scale questions
neither half can answer alone — *is this step comm-bound, which rank is the
straggler, and what effective bandwidth did each traffic class achieve?*
("Exploring the limits of Concurrency in ML Training on Google TPUs" frames
pod throughput as exactly this comm/compute balance; EQuARX-style quantized
collectives need the per-class bandwidth baseline produced here to prove
their wins.)

Everything here is OFFLINE: pure JSON/arithmetic over recorded streams —
no device or backend initialization, no live job required — safe on a
login node over files rsynced from a dead run.

Alignment model
---------------
Per-rank record timestamps (``t``) are that host's wall clock; hosts skew.
Two alignment sources, in preference order:

* **anchor** — ``align/anchor`` meta records written by
  ``Telemetry.anchor()`` immediately after a cross-process barrier: every
  rank stamps the same physical instant through its own clock, so
  subtracting anchor timestamps recovers true per-rank clock offsets,
  including any *constant* straggling.
* **step-median** — fallback when no common anchor exists: the median of
  per-rank deltas over shared step-span boundaries. A rank that is
  consistently late is absorbed into its clock offset under this method
  (only per-step *variation* remains visible) — the report says which
  method produced it.

Restart incarnations append to the same JSONL; extraction slices each
stream to its newest ``flight_recorder/start`` marker so a dead
incarnation's trailing steps (and its stale anchor — a different barrier)
never pollute the resumed timeline. Within an incarnation, step spans
carry a barrier-anchored epoch id (``data.sync``) separating multiple
anchored engines in one process.

Decomposition model
-------------------
Per fused step: ``pod_dur`` = slowest rank's measured step wall.
``compute_floor`` is the comm-free compute estimate — caller-provided
(single-chip calibration) or the minimum observed per-rank step duration
(an optimistic floor: the fastest step bounds compute + unavoidable comm).
Then ``exposed_comm = max(0, pod_dur - compute_floor)`` is communication on
the critical path, and ``comm_bound_frac = exposed_comm / pod_dur``.
Exposed time is attributed to traffic classes proportionally to their
static census bytes (the interconnect serves classes at one effective rate
within a step — an approximation, stated in the report), giving per-class
**effective bandwidth** = class bytes moved / attributed time. With a
``link_gbps`` capacity hint, ``overlapped_comm`` = the part of the analytic
transfer time hidden under compute. Class byte totals come straight from
the census join, so they match the static census exactly by construction —
the tier-1 suite asserts this through a real compiled ZeRO-3 step.
"""
import glob
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# DELIBERATELY stdlib-only, including no sibling imports: the offline CLIs
# (tools/pod_report.py, tools/trace_report.py) load this file by path so a
# login node without jax can still render reports — the telemetry module
# imports the shared helpers below FROM here, never the other way around.

#: Default histogram buckets for durations in seconds (5 ms … 2 min) —
#: telemetry's Histogram default and the pod skew table's resolution.
DURATION_BUCKETS_S = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                      5.0, 10.0, 30.0, 60.0, 120.0)


def histogram_quantile(buckets: Tuple[float, ...], counts: List[int],
                       total: int, q: float) -> Optional[float]:
    """Quantile estimate over Prometheus-style fixed buckets (``counts`` has
    one overflow slot past the last edge): linear interpolation inside the
    bucket the target observation falls in; resolution is the bucket width;
    a target landing in the overflow bucket returns the highest finite
    edge. Shared by ``telemetry.Histogram`` and the offline skew table."""
    if total <= 0 or not 0.0 < q <= 1.0:
        return None
    target = q * total
    cum = 0.0
    for i, edge in enumerate(buckets):
        prev_cum, cum = cum, cum + counts[i]
        if cum >= target:
            lo = buckets[i - 1] if i > 0 else 0.0
            frac = (target - prev_cum) / max(counts[i], 1)
            return lo + (edge - lo) * frac
    return buckets[-1] if buckets else None


def _quantile_summary(values: Sequence[float],
                      qs: Tuple[float, ...] = (0.5, 0.95, 0.99)
                      ) -> Dict[str, Optional[float]]:
    counts = [0] * (len(DURATION_BUCKETS_S) + 1)
    for v in values:
        for i, edge in enumerate(DURATION_BUCKETS_S):
            if v <= edge:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
    return {f"p{int(round(q * 100))}": histogram_quantile(
        DURATION_BUCKETS_S, counts, len(values), q) for q in qs}


#: bump when the ``PodReport.to_dict`` shape changes incompatibly
POD_SCHEMA_VERSION = 1

#: top-level keys every serialized pod report carries (the multichip smoke
#: validates emitted reports against this)
POD_REPORT_KEYS = ("schema_version", "ranks", "truncated_ranks",
                   "missing_ranks", "n_steps", "align", "steps", "skew",
                   "straggler", "decomposition", "census", "comm_hang")

#: ``flightrec_rank3.jsonl`` / ``whatever-rank12.jsonl`` → rank id
_RANK_FILE_RE = re.compile(r"rank(\d+)[^0-9]*\.jsonl$")

#: census traffic classes, heavy movers first (presentation order)
TRAFFIC_CLASSES = ("param_gather", "grad_sync", "other", "scalar_sync")

#: skews below this resolve to "no skew" (host clock + record jitter floor)
_EPS_S = 1e-9


# =========================================================================
# Loading: discovery, salvage, rank inference
# =========================================================================


@dataclass
class RankStream:
    """One rank's parsed flight-recorder stream."""
    rank: int
    path: str
    records: List[Dict[str, Any]]
    truncated: bool = False       # torn tail / unparsable lines were skipped
    salvaged_lines: int = 0       # how many lines could not be parsed


def parse_stream_text(text: str) -> Tuple[List[Dict[str, Any]], int, bool]:
    """Parse JSONL text, salvaging past damage: unparsable lines (a rank
    killed mid-write — the preemption force-dump race) are skipped, not
    fatal. Returns ``(records, bad_line_count, truncated)`` where truncated
    also covers a file whose final line never got its newline."""
    records: List[Dict[str, Any]] = []
    bad = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            bad += 1
            continue
        if isinstance(rec, dict):
            records.append(rec)
        else:
            bad += 1
    truncated = bad > 0 or (bool(text) and not text.endswith("\n"))
    return records, bad, truncated


def infer_rank(path: str, records: Sequence[Dict[str, Any]]) -> Optional[int]:
    """Rank id for a stream: the ``rank<N>`` filename convention first, else
    the LAST ``flight_recorder/start`` meta record (restarts append; the
    newest incarnation is authoritative)."""
    m = _RANK_FILE_RE.search(os.path.basename(path))
    if m:
        return int(m.group(1))
    for rec in reversed(records):
        if rec.get("kind") == "meta" and \
                rec.get("name") == "flight_recorder/start":
            rank = (rec.get("data") or {}).get("rank")
            if rank is not None:
                return int(rank)
    return None


def discover_rank_files(specs: Iterable[str]) -> List[str]:
    """Expand each spec — a directory (its ``flightrec*.jsonl``, else any
    ``*.jsonl``), a glob pattern, or a literal file — into a sorted,
    deduplicated path list. This is what lets the CLIs take
    ``telemetry_logs/`` instead of a hand-enumerated per-rank list."""
    out: List[str] = []
    for spec in specs:
        spec = os.path.expanduser(spec)
        if os.path.isdir(spec):
            hits = sorted(glob.glob(os.path.join(spec, "flightrec*.jsonl")))
            if not hits:
                hits = sorted(glob.glob(os.path.join(spec, "*.jsonl")))
            out.extend(hits)
        elif glob.has_magic(spec):
            out.extend(sorted(glob.glob(spec)))
        else:
            out.append(spec)
    seen, unique = set(), []
    for p in out:
        if p not in seen:
            seen.add(p)
            unique.append(p)
    return unique


def load_rank_streams(specs: Iterable[str]) -> Dict[int, RankStream]:
    """Discover + parse per-rank streams keyed by rank id. Unreadable files
    are dropped (reported by the CLI); a stream whose rank cannot be
    inferred gets the next free non-negative id so nothing is silently
    merged onto an existing rank."""
    streams: Dict[int, RankStream] = {}
    pending: List[RankStream] = []
    for path in discover_rank_files(specs):
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            continue
        records, bad, truncated = parse_stream_text(text)
        if not records:
            continue
        rank = infer_rank(path, records)
        stream = RankStream(rank=-1 if rank is None else rank, path=path,
                            records=records, truncated=truncated,
                            salvaged_lines=bad)
        if rank is None or rank in streams:
            pending.append(stream)
        else:
            streams[rank] = stream
    next_free = 0
    for stream in pending:
        while next_free in streams:
            next_free += 1
        stream.rank = next_free
        streams[next_free] = stream
    return streams


# =========================================================================
# Extraction helpers
# =========================================================================


def _newest_incarnation(records: Sequence[Dict[str, Any]]
                        ) -> Sequence[Dict[str, Any]]:
    """Records belonging to the newest PROCESS incarnation.

    Restart incarnations append to the same rank-local JSONL (crash
    forensics keep the history), and each incarnation restarts its record
    ``seq`` — so the timeline/alignment extraction must only see the
    newest incarnation, or a dead incarnation's trailing steps would fuse
    into (and its stale anchor could mis-align) the resumed run. An
    incarnation is a PROCESS: ``flight_recorder/start`` markers carry the
    writer's pid, and consecutive markers with the newest marker's pid are
    the same incarnation (a second anchored engine in one process is not a
    restart — its earlier siblings' steps stay live, separated by their
    sync epochs). File order is the incarnation order."""
    start = None
    newest_pid = None
    for i in range(len(records) - 1, -1, -1):
        rec = records[i]
        if rec.get("kind") != "meta" or \
                rec.get("name") != "flight_recorder/start":
            continue
        pid = (rec.get("data") or {}).get("pid")
        if start is None:
            start, newest_pid = i, pid
            if pid is None:  # no pid recorded: marker = incarnation
                break
        elif pid == newest_pid:
            start = i  # same process, earlier engine — still live
        else:
            break
    return records if start is None else records[start:]


def _step_spans(records: Sequence[Dict[str, Any]]
                ) -> Dict[Tuple[int, int], Tuple[float, float, bool]]:
    """``{(sync_epoch, step): (t_end_wall, dur_s, compiled)}`` over the
    newest incarnation only (see :func:`_newest_incarnation`); the sync
    epoch separates multiple anchored engines *within* one incarnation.
    ``compiled`` marks a jit cache miss inside the step — its duration is
    compile-contaminated and must not enter the comm/compute split."""
    out: Dict[Tuple[int, int], Tuple[float, float, bool]] = {}
    for rec in _newest_incarnation(records):
        if rec.get("kind") != "span" or rec.get("name") != "step" \
                or "step" not in rec:
            continue
        data = rec.get("data") or {}
        out[(int(data.get("sync", 0)), int(rec["step"]))] = (
            float(rec.get("t", 0.0)), float(rec.get("dur", 0.0)),
            bool(data.get("compiles")))
    return out


def _anchors(records: Sequence[Dict[str, Any]]) -> Dict[int, float]:
    """``{anchor_seq: wall_t}`` from the newest incarnation's
    ``align/anchor`` meta records (an older incarnation's anchor is a
    different barrier — subtracting across them yields garbage offsets).
    Anchors whose barrier failed (``synced: false``) are NOT shared
    instants and are excluded — alignment then falls back to step
    boundaries."""
    out: Dict[int, float] = {}
    for rec in _newest_incarnation(records):
        if rec.get("kind") == "meta" and rec.get("name") == "align/anchor":
            data = rec.get("data") or {}
            if data.get("anchor") is not None and "t" in rec \
                    and data.get("synced", True):
                out[int(data["anchor"])] = float(rec["t"])
    return out


def _comm_marks(records: Sequence[Dict[str, Any]]
                ) -> Tuple[Dict[int, float], List[Dict[str, Any]]]:
    """Watchdog marks from the newest incarnation: ``comm/arm`` events
    (step → wall time the rank arrived at its collective dispatch) and any
    ``comm/hang`` abort events (``comm/watchdog.py``). The arm is the
    pre-dispatch deadline stamp; the per-step ``step`` span is its post
    record — so an arm with no matching span is a step that never came
    back."""
    arms: Dict[int, float] = {}
    hangs: List[Dict[str, Any]] = []
    for rec in _newest_incarnation(records):
        if rec.get("kind") != "event":
            continue
        if rec.get("name") == "comm/arm" and "step" in rec:
            arms[int(rec["step"])] = float(rec.get("t", 0.0))
        elif rec.get("name") == "comm/hang":
            h = dict(rec.get("data") or {})
            if rec.get("step") is not None:
                h.setdefault("step", int(rec["step"]))
            hangs.append(h)
    return arms, hangs


def attribute_comm_hang(streams: Dict[int, "RankStream"], align: "Alignment",
                        spans: Dict[int, Dict]) -> Optional[Dict[str, Any]]:
    """Name the rank that hung the pod.

    Joins each rank's pre-dispatch ``comm/arm`` stamps against its
    completed step spans: on the fatal step, ranks that ARMED but never
    completed were *waiting inside the collective*; a rank that armed
    earlier steps but never armed the fatal one **never arrived** — it is
    the culprit the whole pod was waiting for. When every rank armed (the
    hang was inside the fabric, not before it), the last rank to arm is
    the suspect — the fatal-step extension of the last-arriving-rank
    straggler ledger. Returns ``None`` when no stream shows a watchdog
    abort or a dangling arm."""
    marks = {r: _comm_marks(s.records) for r, s in streams.items()}
    watchdog_ranks = sorted(r for r, (a, h) in marks.items() if a or h)
    if not watchdog_ranks:
        return None
    hang_events = [h for r in watchdog_ranks for h in marks[r][1]]
    done = {r: {s for (_sync, s) in spans.get(r, {})} for r in streams}
    hang_steps = [int(h["step"]) for h in hang_events
                  if h.get("step") is not None]
    if hang_steps:
        step = max(hang_steps)
    else:
        # no (step-carrying) abort record — a salvaged/torn stream may
        # hold a comm/hang without its step field; fall back to the
        # newest arm that never came back
        dangling = [s for r in watchdog_ranks
                    for s in marks[r][0] if s not in done[r]]
        if not dangling:
            return None if not hang_events else {
                "step": None, "arrived_ranks": [], "never_arrived_ranks": [],
                "stuck_ranks": [],
                "detected_by_ranks": sorted(
                    {int(h["rank"]) for h in hang_events
                     if h.get("rank") is not None}),
                "deadline_s": None, "waited_s": None}
        step = max(dangling)
    arrived = sorted(r for r in watchdog_ranks if step in marks[r][0])
    never = sorted(r for r in watchdog_ranks if step not in marks[r][0])
    stuck = sorted(r for r in arrived if step not in done[r])
    detected_by = sorted({int(h["rank"]) for h in hang_events
                          if h.get("rank") is not None}
                         or {r for r in watchdog_ranks if marks[r][1]})
    out: Dict[str, Any] = {
        "step": step,
        "arrived_ranks": arrived,
        "never_arrived_ranks": never,
        "stuck_ranks": stuck,
        "detected_by_ranks": detected_by,
        "deadline_s": max((h.get("deadline_s") or 0.0)
                          for h in hang_events) if hang_events else None,
        "waited_s": max((h.get("waited_s") or 0.0)
                        for h in hang_events) if hang_events else None,
    }
    if never:
        out["culprit_rank"] = never[0]
        out["culprit_reason"] = "never-arrived"
    elif stuck and len(stuck) < len(arrived):
        # some ranks completed the step, these armed and never did: they
        # wedged inside their own collective window (the self-abort shape
        # — independent replicas, or a rank that died mid-collective)
        out["culprit_rank"] = stuck[0]
        out["culprit_reason"] = "never-completed"
    elif arrived:
        # every rank reached its dispatch and none finished: the hang is
        # in the fabric — suspect the rank that arrived last, using
        # aligned clocks so a constant clock offset can't frame an
        # innocent rank
        ts = {r: marks[r][0][step] - align.offsets_s.get(r, 0.0)
              for r in arrived}
        out["culprit_rank"] = max(ts, key=ts.get)
        out["culprit_reason"] = "last-to-arm"
        if len(ts) >= 2:
            out["arm_skew_s"] = round(max(ts.values()) - min(ts.values()), 6)
    return out


def _last_event_data(records: Sequence[Dict[str, Any]],
                     name: str) -> Optional[Dict[str, Any]]:
    for rec in reversed(records):
        if rec.get("name") == name and rec.get("data"):
            return rec["data"]
    return None


def find_census(streams: Dict[int, RankStream]
                ) -> Tuple[Optional[Dict[str, Any]], Optional[int]]:
    """Last ``comm/census`` event across ranks (lowest rank wins ties —
    rank 0 is the conventional emitter). Returns ``(classes_summary,
    source_rank)``; accepts both the bare ``CollectiveClasses.summary()``
    dict and a ``{"classes": ..., ...context}`` wrapper."""
    for rank in sorted(streams):
        data = _last_event_data(streams[rank].records, "comm/census")
        if data is None:
            continue
        classes = data.get("classes", data)
        if isinstance(classes, dict) and any(
                isinstance(v, dict) and "total_bytes" in v
                for v in classes.values()):
            return classes, rank
    return None, None


def _measured_xla_bytes(streams: Dict[int, RankStream]) -> Optional[int]:
    """Total bytes of the measured post-compile op mix (``comm/snapshot``
    records' ``xla::`` keys) — the census join's runtime cross-check."""
    for rank in sorted(streams):
        snap = _last_event_data(streams[rank].records, "comm/snapshot")
        if not snap:
            continue
        xla = {k: v for k, v in snap.items()
               if isinstance(v, dict) and k.startswith("xla::")}
        if xla:
            return sum(int(v.get("total_bytes", 0)) for v in xla.values())
    return None


def _median(values: Sequence[float]) -> float:
    from statistics import median

    return float(median(values))


# =========================================================================
# Clock alignment
# =========================================================================


@dataclass
class Alignment:
    #: "anchor" | "step-median" | "mixed" | "single" — how offsets were
    #: derived ("mixed": some ranks anchored, others fell back per-rank)
    method: str
    offsets_s: Dict[int, float]       # rank -> subtract from its wall times
    reference_rank: int
    unaligned_ranks: List[int] = field(default_factory=list)


def align_streams(streams: Dict[int, RankStream],
                  spans: Optional[Dict[int, Dict]] = None) -> Alignment:
    """Per-rank clock offsets relative to the lowest rank with step spans.
    ``spans`` accepts the precomputed per-rank :func:`_step_spans` maps so
    :func:`fuse_pod` walks each record list once, not twice.

    Per rank, an anchor shared with the reference is preferred (true clock
    offset — constant straggling stays visible as skew); the median delta
    over shared step-span boundaries is the fallback (constant straggling
    is absorbed into the offset; only per-step variation remains). The
    choice is PER RANK: one truncated stream that lost its anchor degrades
    itself, not the whole pod. Ranks sharing neither an anchor nor any
    step with the reference are reported unaligned and excluded from
    skew."""
    if spans is None:
        spans = {r: _step_spans(s.records) for r, s in streams.items()}
    anchors = {r: _anchors(s.records) for r, s in streams.items()}
    ranks_with_steps = [r for r in sorted(streams) if spans[r]]
    if not ranks_with_steps:
        ref = min(streams) if streams else 0
        return Alignment(method="single", offsets_s={}, reference_rank=ref,
                         unaligned_ranks=sorted(streams))
    # prefer an ANCHORED reference: if rank 0's truncated stream lost its
    # anchor record, comparing everyone against it would degrade the whole
    # pod to step-median even though ranks 1..N share valid anchors
    anchored = [r for r in ranks_with_steps if anchors[r]]
    ref = anchored[0] if anchored else ranks_with_steps[0]
    if len(streams) == 1:
        return Alignment(method="single", offsets_s={ref: 0.0},
                         reference_rank=ref)

    offsets: Dict[int, float] = {ref: 0.0}
    unaligned: List[int] = []
    methods_used = set()
    for r in sorted(streams):
        if r == ref:
            continue
        shared_anchors = set(anchors[r]) & set(anchors[ref])
        if shared_anchors:
            seq = max(shared_anchors)  # newest barrier = tightest clocks
            offsets[r] = anchors[r][seq] - anchors[ref][seq]
            methods_used.add("anchor")
            continue
        shared = sorted(set(spans[r]) & set(spans[ref]))
        if shared:
            offsets[r] = _median([spans[r][k][0] - spans[ref][k][0]
                                  for k in shared])
            methods_used.add("step-median")
        else:
            unaligned.append(r)
    method = (methods_used.pop() if len(methods_used) == 1
              else "mixed" if methods_used else "single")
    return Alignment(method=method, offsets_s=offsets, reference_rank=ref,
                     unaligned_ranks=unaligned)


# =========================================================================
# Fusion + decomposition
# =========================================================================


@dataclass
class PodReport:
    """The fused cluster view. ``to_dict()`` is the stable serialized
    schema (``POD_REPORT_KEYS``); ``render()`` the operator tables;
    ``events()``/``publish()`` feed the ``Pod/*`` family back through the
    monitor registry on rank 0."""
    ranks: List[int]
    truncated_ranks: List[int]
    missing_ranks: List[int]          # present but no usable step spans
    align: Alignment
    steps: List[Dict[str, Any]]       # fused per-step rows, step order
    skew: Dict[str, Optional[float]]  # p50/p95/p99/max seconds
    straggler_counts: Dict[int, int]  # rank -> times it arrived last
    straggler_lateness_s: Dict[int, float]
    compute_floor_s: Optional[float]
    compute_floor_source: str         # "provided" | "min-observed" | "none"
    comm_bound_frac: Optional[float]  # mean over steps
    exposed_comm_s: float
    overlapped_comm_s: Optional[float]
    classes: Dict[str, Dict[str, Any]]
    census_rank: Optional[int]
    census_total_bytes: Optional[int]
    measured_xla_bytes: Optional[int]
    #: collective-hang attribution (attribute_comm_hang): which rank never
    #: arrived at the fatal step's dispatch — None when the run saw none
    comm_hang: Optional[Dict[str, Any]] = None
    source_files: Dict[int, str] = field(default_factory=dict)

    # ------------------------------------------------------------- schema
    @property
    def n_steps(self) -> int:
        return len(self.steps)

    @property
    def bytes_match(self) -> Optional[bool]:
        if self.census_total_bytes is None or self.measured_xla_bytes is None:
            return None
        return self.census_total_bytes == self.measured_xla_bytes

    def to_dict(self) -> Dict[str, Any]:
        return {
            "schema_version": POD_SCHEMA_VERSION,
            "ranks": list(self.ranks),
            "truncated_ranks": list(self.truncated_ranks),
            "missing_ranks": list(self.missing_ranks),
            "n_steps": self.n_steps,
            "align": {"method": self.align.method,
                      "reference_rank": self.align.reference_rank,
                      "offsets_s": {str(r): round(o, 6) for r, o in
                                    self.align.offsets_s.items()},
                      "unaligned_ranks": list(self.align.unaligned_ranks)},
            "steps": self.steps,
            "skew": self.skew,
            "straggler": {
                "counts": {str(r): c for r, c in
                           self.straggler_counts.items()},
                "lateness_s": {str(r): round(v, 6) for r, v in
                               self.straggler_lateness_s.items()}},
            "decomposition": {
                "compute_floor_s": self.compute_floor_s,
                "compute_floor_source": self.compute_floor_source,
                "comm_bound_frac": self.comm_bound_frac,
                "exposed_comm_s": round(self.exposed_comm_s, 6),
                "overlapped_comm_s": self.overlapped_comm_s,
                "classes": self.classes},
            "census": {"source_rank": self.census_rank,
                       "total_bytes_per_step": self.census_total_bytes,
                       "measured_xla_bytes": self.measured_xla_bytes,
                       "bytes_match": self.bytes_match},
            "comm_hang": self.comm_hang,
        }

    # ------------------------------------------------------------- events
    def events(self, step: int = 0) -> List[Tuple[str, Any, int]]:
        """Scalar ``Pod/*`` events (declared family prefix) for a rank-0
        MonitorMaster: skew quantiles, comm-bound fraction, per-class
        effective bandwidth, straggler histogram."""
        ev: List[Tuple[str, Any, int]] = [
            ("Pod/ranks", float(len(self.ranks)), step),
            ("Pod/steps", float(self.n_steps), step),
            ("Pod/exposed_comm_s", self.exposed_comm_s, step)]
        for q in ("p50", "p95", "p99"):
            v = self.skew.get(q)
            if v is not None:
                ev.append((f"Pod/skew_{q}_s", v, step))
        if self.comm_bound_frac is not None:
            ev.append(("Pod/comm_bound_frac", self.comm_bound_frac, step))
        # data-dependent members use the Comm/-family dot convention
        # (Group/base.tail) so the static event-name lint can resolve the
        # literal base against the registry
        for cls, d in self.classes.items():
            if d.get("effective_gbps") is not None:
                ev.append((f"Pod/bw.{cls}_gbps", d["effective_gbps"], step))
        for rank, count in sorted(self.straggler_counts.items()):
            ev.append((f"Pod/straggler.rank{rank}", float(count), step))
        if self.comm_hang is not None:
            if self.comm_hang.get("step") is not None:
                ev.append(("Pod/comm_hang.step",
                           float(self.comm_hang["step"]), step))
            if self.comm_hang.get("culprit_rank") is not None:
                ev.append(("Pod/comm_hang.culprit_rank",
                           float(self.comm_hang["culprit_rank"]), step))
        return ev

    def publish(self, registry: Any = None, monitor: Any = None,
                step: int = 0) -> List[Tuple[str, Any, int]]:
        """Feed the ``Pod/*`` events into a :class:`MetricsRegistry` (as
        gauges/counters) and optionally a ``MonitorMaster`` — the rank-0
        feedback path. Returns the event list either way."""
        ev = self.events(step)
        if registry is not None:
            for name, value, _step in ev:
                if name.startswith("Pod/straggler."):
                    c = registry.counter(name)
                    c.incr(int(value) - c.value)
                else:
                    registry.gauge(name).set(value)
        if monitor is not None:
            monitor.write_events(ev)
        return ev

    # ------------------------------------------------------------- render
    def render(self, last: int = 20) -> str:
        out: List[str] = []
        out.append(f"pod report — {len(self.ranks)} rank(s), "
                   f"{self.n_steps} fused step(s), clock alignment: "
                   f"{self.align.method}")
        for rank in self.ranks:
            notes = []
            if rank in self.truncated_ranks:
                notes.append("TRUNCATED (salvaged partial stream)")
            if rank in self.missing_ranks:
                notes.append("no step spans")
            if rank in self.align.unaligned_ranks:
                notes.append("unalignable (excluded from skew)")
            off = self.align.offsets_s.get(rank)
            off_txt = "" if off is None else (
                f"offset {off * 1e3:+.1f}ms" if abs(off) < 10.0
                else f"offset {off:+.1f}s")
            src = self.source_files.get(rank, "")
            out.append(f"  rank{rank:<4}{off_txt:<24}{src}"
                       + (f"  <-- {', '.join(notes)}" if notes else ""))

        out.append("")
        out.append(f"step timeline (last {min(last, self.n_steps)} of "
                   f"{self.n_steps})")
        out.append(f"{'step':>8}{'pod dur':>12}{'skew':>10}"
                   f"{'straggler':>11}{'comm-bound':>12}")
        for row in self.steps[-last:]:
            cb = (f"{100 * row['comm_bound_frac']:.1f}%"
                  if row.get("comm_bound_frac") is not None
                  else ("compile" if row.get("compiled") else "-"))
            skew = (_fmt_s(row["skew_s"]) if row.get("skew_s") is not None
                    else "-")
            strag = (f"rank{row['straggler']}"
                     if row.get("straggler") is not None else "-")
            out.append(f"{row['step']:>8}{_fmt_s(row['dur_s']):>12}"
                       f"{skew:>10}{strag:>11}{cb:>12}")
        if not self.steps:
            out.append("  (no fusable step spans)")

        out.append("")
        out.append("arrival skew (last-arriving-rank attribution)")
        if len(self.ranks) < 2 or not any(
                r.get("skew_s") is not None for r in self.steps):
            out.append("  (single aligned rank — no cross-rank skew)")
        else:
            qs = ", ".join(
                f"{q}={_fmt_s(self.skew[q])}" for q in ("p50", "p95", "p99")
                if self.skew.get(q) is not None)
            out.append(f"  quantiles: {qs}  max={_fmt_s(self.skew['max'])}")
            out.append(f"  {'rank':<8}{'times last':>12}"
                       f"{'total lateness':>16}")
            for rank in sorted(self.straggler_counts):
                out.append(
                    f"  rank{rank:<4}{self.straggler_counts[rank]:>12}"
                    f"{_fmt_s(self.straggler_lateness_s.get(rank, 0.0)):>16}")

        if self.comm_hang is not None:
            h = self.comm_hang
            out.append("")
            out.append("collective hang (watchdog abort)")
            who = (f"rank{h['culprit_rank']} ({h.get('culprit_reason')})"
                   if h.get("culprit_rank") is not None else "unattributed")
            out.append(f"  step {h['step']}: culprit {who}")
            out.append(f"  armed (arrived at dispatch): "
                       f"{h.get('arrived_ranks')}  never arrived: "
                       f"{h.get('never_arrived_ranks')}")
            detail = []
            if h.get("deadline_s") is not None:
                detail.append(f"deadline {h['deadline_s']:.1f}s")
            if h.get("waited_s") is not None:
                detail.append(f"waited {h['waited_s']:.1f}s")
            if h.get("arm_skew_s") is not None:
                detail.append(f"arm skew {_fmt_s(h['arm_skew_s'])}")
            if h.get("detected_by_ranks"):
                detail.append(f"detected by rank(s) "
                              f"{h['detected_by_ranks']}")
            if detail:
                out.append(f"  {', '.join(detail)}")

        out.append("")
        out.append("comm/compute decomposition")
        if self.compute_floor_s is None:
            out.append("  (no steps — nothing to decompose)")
        else:
            out.append(f"  compute floor: {_fmt_s(self.compute_floor_s)} "
                       f"({self.compute_floor_source})")
            out.append(f"  exposed comm:  {_fmt_s(self.exposed_comm_s)} "
                       f"total, comm_bound_frac="
                       f"{100 * (self.comm_bound_frac or 0.0):.1f}% mean")
            if self.overlapped_comm_s is not None:
                out.append(f"  overlapped comm: "
                           f"{_fmt_s(self.overlapped_comm_s)} total "
                           f"(analytic demand hidden under compute)")
        if self.classes:
            out.append(f"  {'class':<14}{'ops/step':>9}{'MB/step':>10}"
                       f"{'time':>10}{'eff GB/s':>10}")
            for cls in TRAFFIC_CLASSES:
                d = self.classes.get(cls)
                if d is None:
                    continue
                bw = (f"{d['effective_gbps']:.2f}"
                      if d.get("effective_gbps") is not None else
                      ("overlap" if d["bytes_per_step"] else "-"))
                out.append(f"  {cls:<14}{d['count']:>9}"
                           f"{d['bytes_per_step'] / 2**20:>10.2f}"
                           f"{_fmt_s(d['attributed_s']):>10}{bw:>10}")
            match = self.bytes_match
            check = ("MATCH" if match else "MISMATCH") if match is not None \
                else "no comm/snapshot in streams"
            out.append(f"  census {self.census_total_bytes} B/step vs "
                       f"measured xla:: op mix "
                       f"{self.measured_xla_bytes} B: {check}")
        else:
            out.append("  (no comm/census record in any stream — run with "
                       "engine.emit_comm_census() for the per-class table)")
        return "\n".join(out)


def _fmt_s(sec: float) -> str:
    if sec >= 1.0:
        return f"{sec:.2f}s"
    if sec >= 1e-3:
        return f"{sec * 1e3:.1f}ms"
    return f"{sec * 1e6:.0f}us"


def fuse_pod(streams: Dict[int, RankStream],
             census: Optional[Dict[str, Any]] = None,
             compute_s: Optional[float] = None,
             link_gbps: Optional[float] = None) -> PodReport:
    """Fuse per-rank streams into a :class:`PodReport`.

    ``census`` overrides the in-stream ``comm/census`` record (the classes
    summary dict); ``compute_s`` overrides the observed compute floor;
    ``link_gbps`` enables the exposed-vs-overlapped split against an
    analytic transfer-time demand."""
    spans = {r: _step_spans(s.records) for r, s in streams.items()}
    align = align_streams(streams, spans=spans)
    aligned_ranks = [r for r in sorted(streams)
                     if spans[r] and r in align.offsets_s]
    missing = [r for r in sorted(streams) if not spans[r]]

    # fused step rows: keys shared semantics — any (sync, step) seen by at
    # least one aligned rank; cross-rank skew only where >=2 ranks share it
    all_keys = sorted({k for r in aligned_ranks for k in spans[r]})
    steps: List[Dict[str, Any]] = []
    skews: List[float] = []
    straggler_counts: Dict[int, int] = {r: 0 for r in aligned_ranks}
    straggler_lateness: Dict[int, float] = {r: 0.0 for r in aligned_ranks}
    min_rank_dur: Optional[float] = None
    for key in all_keys:
        present = [r for r in aligned_ranks if key in spans[r]]
        durs = {r: spans[r][key][1] for r in present}
        ends = {r: spans[r][key][0] - align.offsets_s[r] for r in present}
        compiled = any(spans[r][key][2] for r in present)
        if not compiled:
            for d in durs.values():
                if d > 0:
                    min_rank_dur = d if min_rank_dur is None \
                        else min(min_rank_dur, d)
        row: Dict[str, Any] = {"step": key[1], "sync": key[0],
                               "dur_s": max(durs.values()),
                               "ranks": len(present),
                               "compiled": compiled}
        if len(present) >= 2:
            first = min(ends.values())
            last_rank = max(ends, key=ends.get)
            skew = max(0.0, ends[last_rank] - first)
            row["skew_s"] = skew
            row["straggler"] = last_rank
            skews.append(skew)
            if skew > _EPS_S:
                straggler_counts[last_rank] += 1
                straggler_lateness[last_rank] += skew
        steps.append(row)

    skew_summary: Dict[str, Optional[float]] = {
        "p50": None, "p95": None, "p99": None, "max": None}
    if skews:
        skew_summary.update(_quantile_summary(skews), max=max(skews))

    # ---------------------------------------------------- decomposition
    if census is None:
        census, census_rank = find_census(streams)
    else:
        census = census.get("classes", census)
        census_rank = None
    measured = _measured_xla_bytes(streams)
    census_total = (sum(int(census[c]["total_bytes"]) for c in census)
                    if census else None)

    if compute_s is not None:
        floor, floor_src = float(compute_s), "provided"
    elif min_rank_dur is not None:
        floor, floor_src = min_rank_dur, "min-observed"
    else:
        floor, floor_src = None, "none"

    exposed_total = 0.0
    cb_fracs: List[float] = []
    overlapped_total: Optional[float] = None
    if floor is not None:
        data_bytes = census_total or 0
        demand_s = (data_bytes / (link_gbps * 1e9)
                    if link_gbps and data_bytes else None)
        if demand_s is not None:
            overlapped_total = 0.0
        for row in steps:
            if row["compiled"]:
                # a jit cache miss inflates this step's wall with compile
                # time — goodput's compile bucket, not communication
                continue
            dur = row["dur_s"]
            exposed = max(0.0, dur - floor)
            row["exposed_comm_s"] = round(exposed, 9)
            row["comm_bound_frac"] = exposed / dur if dur > 0 else 0.0
            cb_fracs.append(row["comm_bound_frac"])
            exposed_total += exposed
            if demand_s is not None:
                overlapped = max(0.0, min(demand_s, dur) - exposed)
                row["overlapped_comm_s"] = round(overlapped, 9)
                overlapped_total += overlapped

    classes: Dict[str, Dict[str, Any]] = {}
    if census:
        data_total = sum(int(census[c]["total_bytes"]) for c in census) or 1
        # bandwidth is a clean-sample ratio: exposed_total sums CLEAN
        # (non-compile) steps only, so the byte numerator must count the
        # same steps — total_bytes still reports the whole run's movement
        n_clean = sum(1 for row in steps if not row["compiled"])
        for cls in census:
            b = int(census[cls]["total_bytes"])
            share = b / data_total
            attributed = share * exposed_total
            clean_moved = b * n_clean
            classes[cls] = {
                "count": int(census[cls].get("count", 0)),
                "bytes_per_step": b,
                "total_bytes": b * len(steps),
                "attributed_s": attributed,
                "effective_gbps": (round(clean_moved / attributed / 1e9, 6)
                                   if attributed > 1e-12 and clean_moved
                                   else None),
            }

    return PodReport(
        ranks=sorted(streams),
        truncated_ranks=[r for r in sorted(streams) if streams[r].truncated],
        missing_ranks=missing,
        align=align,
        steps=steps,
        skew=skew_summary,
        straggler_counts=straggler_counts,
        straggler_lateness_s=straggler_lateness,
        compute_floor_s=floor,
        compute_floor_source=floor_src,
        comm_bound_frac=(sum(cb_fracs) / len(cb_fracs)) if cb_fracs else None,
        exposed_comm_s=exposed_total,
        overlapped_comm_s=overlapped_total,
        classes=classes,
        census_rank=census_rank,
        census_total_bytes=census_total,
        measured_xla_bytes=measured,
        comm_hang=attribute_comm_hang(streams, align, spans),
        source_files={r: s.path for r, s in streams.items()},
    )


def validate_pod_report(d: Dict[str, Any]) -> List[str]:
    """Schema check for a serialized pod report (the multichip smoke gate).
    Returns a list of problems — empty means valid."""
    problems = [f"missing key: {k}" for k in POD_REPORT_KEYS if k not in d]
    if problems:
        return problems
    if d["schema_version"] != POD_SCHEMA_VERSION:
        problems.append(f"schema_version {d['schema_version']} != "
                        f"{POD_SCHEMA_VERSION}")
    if not isinstance(d["steps"], list):
        problems.append("steps is not a list")
    else:
        for i, row in enumerate(d["steps"]):
            for k in ("step", "dur_s"):
                if k not in row:
                    problems.append(f"steps[{i}] missing {k}")
    for k in ("method", "offsets_s", "reference_rank"):
        if k not in d["align"]:
            problems.append(f"align missing {k}")
    dec = d["decomposition"]
    for k in ("compute_floor_s", "comm_bound_frac", "exposed_comm_s",
              "classes"):
        if k not in dec:
            problems.append(f"decomposition missing {k}")
    cb = dec.get("comm_bound_frac")
    if cb is not None and not (isinstance(cb, (int, float))
                               and -1e-9 <= cb <= 1.0 + 1e-9):
        problems.append(f"comm_bound_frac out of [0,1]: {cb}")
    for cls, row in (dec.get("classes") or {}).items():
        for k in ("count", "bytes_per_step", "attributed_s",
                  "effective_gbps"):
            if k not in row:
                problems.append(f"class {cls} missing {k}")
    ch = d.get("comm_hang")
    if ch is not None:
        for k in ("step", "arrived_ranks", "never_arrived_ranks"):
            if k not in ch:
                problems.append(f"comm_hang missing {k}")
    return problems


def pod_report_from_paths(specs: Iterable[str],
                          census: Optional[Dict[str, Any]] = None,
                          compute_s: Optional[float] = None,
                          link_gbps: Optional[float] = None
                          ) -> Optional[PodReport]:
    """One-call convenience: discover + load + fuse. ``None`` when no spec
    yields any records."""
    streams = load_rank_streams(specs)
    if not streams:
        return None
    return fuse_pod(streams, census=census, compute_s=compute_s,
                    link_gbps=link_gbps)
