"""MFU ledger core: region registry, HLO op→region map, trace join.

The step-time attribution instrument ("Exploring the limits of Concurrency
in ML Training on Google TPUs" does this per-phase attribution at pod
scale): the engine wraps model phases in ``jax.named_scope("mfu.<region>")``
labels, XLA propagates those labels into every compiled instruction's
``metadata={op_name=...}``, and the profiler's Chrome-trace window carries
one timed event per executed HLO op named by instruction. This module owns
the three joins between those worlds:

* :func:`build_opmap` — compiled-HLO text → ``{instruction: {region,
  category}}`` (the named_scope metadata is read here; collectives override
  to the ``collective`` region by opcode, since the partitioner inserts
  them with no scope).
* :func:`parse_trace` — ``trace.json.gz`` (Chrome-trace) → timed op events,
  with truncation salvage: a torn gzip / half-written JSON from a killed
  run yields everything parseable plus a ``truncated`` flag, never a crash
  (the ``monitor/pod.py`` contract).
* :func:`ledger` — the MFU ledger itself: achieved MFU, the gap waterfall
  (hardware peak → roofline-achievable → measured), per-region
  measured-vs-achievable with bound-by verdicts, top time sinks, and the
  region-sum↔step-time reconciliation.

DELIBERATELY STDLIB-ONLY: ``tools/mfu_report.py`` loads this file by path
on jax-less login nodes (the ``pod.py`` contract — telemetry/analysis
import FROM here, never the reverse). :func:`region_scope` is the one
jax-touching helper and imports it lazily at call time.
"""
import gzip
import json
import os
import re
import zlib
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

#: Canonical attribution regions. The first block are SCOPE regions — model/
#: engine code wraps phases in ``jax.named_scope("mfu.<name>")`` (via
#: :func:`region_scope`) and dslint's ``undeclared-region`` rule rejects any
#: label outside this set. The rest are DERIVED: ``collective`` is assigned
#: by opcode (partitioner-inserted traffic carries no scope), ``host`` is
#: the measured step-wall minus device-busy gap, ``other`` is every mapped
#: op with no scope (norm chains, loss-scale bookkeeping, data movement).
SCOPE_REGIONS = ("embed", "attn", "mlp", "head", "loss", "optimizer")
DERIVED_REGIONS = ("collective", "other", "host")
REGIONS = SCOPE_REGIONS + DERIVED_REGIONS

#: named_scope label prefix — ``mfu.attn`` etc. Kept short and distinctive
#: so the metadata regex can't false-positive on user scopes.
SCOPE_PREFIX = "mfu."

_REGION_RE = re.compile(r"mfu\.([A-Za-z0-9_]+)")

#: HLO opcodes that are cross-device traffic regardless of scope (async
#: halves included — time is attributed to whichever half the runtime bills)
COLLECTIVE_OPCODES = frozenset({
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast",
    "all-reduce-start", "all-reduce-done", "all-gather-start",
    "all-gather-done", "collective-permute-start",
    "collective-permute-done", "send", "recv", "send-done", "recv-done",
})

#: coarse HLO category buckets for the by-category time split
_CATEGORY = (
    ("dot", ("dot", "convolution")),
    ("collective", tuple(COLLECTIVE_OPCODES)),
    ("fusion", ("fusion",)),
    ("reduce", ("reduce", "reduce-window", "scatter", "gather")),
    ("data-movement", ("copy", "transpose", "broadcast", "reshape",
                       "bitcast", "concatenate", "slice", "dynamic-slice",
                       "dynamic-update-slice", "pad", "iota")),
    ("control", ("while", "conditional", "call", "tuple",
                 "get-tuple-element", "parameter", "constant")),
)


def region_scope(name: str):
    """``jax.named_scope`` for a declared MFU region — the ONE sanctioned
    way model/engine code labels a phase (a bare ``named_scope("mfu.x")``
    with a typo'd region would silently orphan its time; dslint's
    ``undeclared-region`` rule rejects it, and this helper raises)."""
    if name not in SCOPE_REGIONS:
        raise ValueError(f"undeclared MFU region {name!r}; declared scope "
                         f"regions: {SCOPE_REGIONS} (monitor/mfu.py)")
    import jax  # lazy: this module must import stdlib-only

    return jax.named_scope(SCOPE_PREFIX + name)


def region_of(op_name: str) -> Optional[str]:
    """Region encoded in an HLO ``metadata op_name`` path (e.g.
    ``jit(f)/transpose(jvp(mfu.attn))/dot_general`` → ``attn``). The LAST
    match wins: an inner scope refines an outer one. ``None`` = unscoped."""
    found = _REGION_RE.findall(op_name or "")
    if not found:
        return None
    name = found[-1]
    return name if name in SCOPE_REGIONS else None


def _category_of(opcode: str) -> str:
    for cat, ops in _CATEGORY:
        if opcode in ops:
            return cat
    return "other"


# one HLO instruction definition: `  %name = type opcode(...), ...` or
# `  ROOT %name = ...`. Names may carry dots/dashes (`dot.12`,
# `subtract_exponential_fusion`); the result type may be a parenthesized
# TUPLE with internal spaces — `(f32[8]{0}, s32[])` — which is exactly what
# `while` loops and combined (variadic) all-reduces produce, i.e. the scan
# trunk and the main grad-sync traffic this instrument exists to name. On
# TPU the layouts inside the tuple carry one level of NESTED parens
# (tiling annotations: `bf16[4096]{0:T(1024)}`), so the tuple branch must
# admit them.
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*"
    r"(?:\((?:[^()]|\([^()]*\))*\)|[^\s]+)\s+"
    r"([a-z][\w\-]*)\(")
_METADATA_RE = re.compile(r'metadata=\{[^}]*op_name="([^"]*)"')


def build_opmap(hlo_text: str) -> Dict[str, Dict[str, str]]:
    """Compiled-HLO text → ``{instruction_name: {"region", "category",
    "opcode"}}`` for every instruction in every computation (trace events
    are named by instruction; names are unique module-wide).

    Region precedence: collective opcode > ``mfu.<region>`` scope in the
    op_name metadata > ``other``. Trivial bookkeeping opcodes (parameter/
    constant/tuple plumbing) are skipped — they never carry measured time.
    """
    out: Dict[str, Dict[str, str]] = {}
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, opcode = m.group(1), m.group(2)
        if opcode in ("parameter", "constant", "tuple", "get-tuple-element"):
            continue
        if opcode in COLLECTIVE_OPCODES:
            region = "collective"
        else:
            meta = _METADATA_RE.search(line)
            region = region_of(meta.group(1)) if meta else None
            region = region or "other"
        out[name] = {"region": region, "category": _category_of(opcode),
                     "opcode": opcode}
    return out


# ------------------------------------------------------------------ trace IO
def _salvage_events(text: str) -> Tuple[List[Dict[str, Any]], bool]:
    """Chrome-trace JSON salvage: when ``json.loads`` fails (torn tail),
    walk the ``traceEvents`` array with a brace counter and keep every
    COMPLETE event object. Returns (events, salvaged_flag)."""
    try:
        d = json.loads(text)
        return list(d.get("traceEvents", [])), False
    except ValueError:
        pass
    events: List[Dict[str, Any]] = []
    idx = text.find('"traceEvents"')
    if idx < 0:
        return events, True
    idx = text.find("[", idx)
    if idx < 0:
        return events, True
    depth = 0
    start = None
    in_str = False
    esc = False
    for i in range(idx + 1, len(text)):
        c = text[i]
        if in_str:
            if esc:
                esc = False
            elif c == "\\":
                esc = True
            elif c == '"':
                in_str = False
            continue
        if c == '"':
            in_str = True
        elif c == "{":
            if depth == 0:
                start = i
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0 and start is not None:
                try:
                    events.append(json.loads(text[start:i + 1]))
                except ValueError:
                    pass
                start = None
        elif c == "]" and depth == 0:
            break
    return events, True


def parse_trace(path: str) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """Load one Chrome-trace file (``.json`` or ``.json.gz``) with
    truncation salvage. Returns ``(duration_events, meta)`` where
    duration_events are the ``"ph" == "X"`` records and ``meta`` carries
    ``{"truncated": bool, "n_events": int, "path": str}``. A torn gzip
    stream (killed mid-write) decompresses to its last whole deflate block
    and the JSON salvage keeps every complete event — flagged, not fatal."""
    truncated = False
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return [], {"truncated": True, "n_events": 0, "path": path}
    if path.endswith(".gz") or raw[:2] == b"\x1f\x8b":
        try:
            text = gzip.decompress(raw).decode("utf-8", "replace")
        except (OSError, EOFError, zlib.error):
            # torn gzip: stream-decompress whatever whole blocks exist
            d = zlib.decompressobj(wbits=31)
            try:
                text = d.decompress(raw).decode("utf-8", "replace")
            except zlib.error:
                text = ""
            truncated = True
    else:
        text = raw.decode("utf-8", "replace")
    events, salvaged = _salvage_events(text)
    truncated = truncated or salvaged
    dur_events = [e for e in events
                  if e.get("ph") == "X" and "ts" in e and "dur" in e]
    return dur_events, {"truncated": truncated, "n_events": len(dur_events),
                        "path": path}


def find_trace(root: str) -> Optional[str]:
    """Newest ``*.trace.json.gz`` (or ``trace.json``) under ``root`` — the
    ``jax.profiler`` layout is ``<root>/plugins/profile/<run>/<host>.trace
    .json.gz``; a bare file path passes through."""
    if os.path.isfile(root):
        return root
    hits: List[str] = []
    for dirpath, _dirnames, files in os.walk(root):
        for f in files:
            if f.endswith((".trace.json.gz", "trace.json.gz", "trace.json")):
                hits.append(os.path.join(dirpath, f))
    return max(hits, key=lambda p: os.path.getmtime(p)) if hits else None


# ---------------------------------------------------------------- measurement
def _union_us(intervals: Iterable[Tuple[float, float]]) -> float:
    """Total length of the union of [start, end) intervals (µs)."""
    ivs = sorted(intervals)
    total = 0.0
    cur_s = cur_e = None
    for s, e in ivs:
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    if cur_e is not None:
        total += cur_e - cur_s
    return total


def _self_segments(events: List[Dict[str, Any]],
                   opmap: Dict[str, Dict[str, str]]
                   ) -> List[Tuple[float, float, str, str]]:
    """Flatten one THREAD's (properly nested) op events into disjoint
    ``(start, end, region, category)`` self-time segments: a ``while`` op's
    event covers its whole loop while every body op is ALSO recorded inside
    it — a plain duration sum double-counts that containment (observed
    1.7× on the CPU executor). Each event owns only the parts of its span
    not covered by a nested event."""
    es = sorted((e for e in events), key=lambda e: (e["ts"], -e["dur"]))
    segs: List[Tuple[float, float, str, str]] = []
    # stack entries: [end, cursor, region, category]; cursor = where this
    # event's uncovered span resumes after the current child
    stack: List[List[Any]] = []

    def pop_to(ts: float) -> None:
        while stack and stack[-1][0] <= ts:
            end, cursor, region, cat = stack.pop()
            if end > cursor:
                segs.append((cursor, end, region, cat))
            if stack:
                stack[-1][1] = max(stack[-1][1], end)

    for e in es:
        ts = float(e["ts"])
        end = ts + float(e["dur"])
        info = opmap[str(e["name"])]
        pop_to(ts)
        if stack and stack[-1][1] < ts:
            # parent's uncovered span up to this child
            segs.append((stack[-1][1], ts, stack[-1][2], stack[-1][3]))
            stack[-1][1] = ts
        stack.append([end, ts, info["region"], info["category"]])
    pop_to(float("inf"))
    return segs


def measure_regions(events: Sequence[Dict[str, Any]],
                    opmap: Dict[str, Dict[str, str]],
                    steps: int = 1) -> Dict[str, Any]:
    """Join timed trace events against the opmap into per-region and
    per-HLO-category seconds (per step).

    Attribution is WALL-CLOCK-exact, not duration-sum: per thread, nested
    events flatten to self-time segments (:func:`_self_segments`); across
    threads, every instant of the mapped-op union timeline is split evenly
    among the threads busy at that instant (the executor genuinely runs
    independent ops concurrently — billing both in full would overcount).
    So ``sum(regions) == mapped-op union`` by construction, and the ledger
    reconciliation catches the one thing that can still go missing:
    op events whose name is NOT in the opmap (``orphan_s``) — exactly what
    a typo'd/missing scope or a stale opmap produces.

    ``device_busy_s`` is the union over ALL op events (an event counts as
    an op when its name is in the opmap or it carries an ``hlo_op`` arg),
    mapped or not."""
    steps = max(1, int(steps))
    by_thread: Dict[Tuple[Any, Any], List[Dict[str, Any]]] = {}
    all_intervals: List[Tuple[float, float]] = []
    n_mapped = n_orphan = 0
    for e in events:
        name = str(e.get("name", ""))
        mapped = name in opmap
        is_op = mapped or "hlo_op" in (e.get("args") or {})
        if not is_op:
            continue
        ts = float(e["ts"])
        all_intervals.append((ts, ts + float(e["dur"])))
        if not mapped:
            n_orphan += 1
            continue
        n_mapped += 1
        by_thread.setdefault((e.get("pid"), e.get("tid")), []).append(e)

    # per-thread disjoint self segments → global even-split sweep
    threads = [
        _self_segments(es, opmap) for es in by_thread.values()]
    points: List[Tuple[float, int, int, str, str]] = []
    for ti, segs in enumerate(threads):
        for s, e, region, cat in segs:
            points.append((s, 1, ti, region, cat))
            points.append((e, -1, ti, region, cat))
    # closes (-1) before opens (+1) at equal t: per-thread segments are
    # disjoint, so a segment ending exactly where the next begins must
    # release the thread slot before the successor claims it
    points.sort(key=lambda p: (p[0], p[1]))
    regions: Dict[str, float] = {}
    categories: Dict[str, float] = {}
    active: Dict[int, Tuple[str, str]] = {}
    prev = None
    mapped_union = 0.0
    for t, kind, ti, region, cat in points:
        if prev is not None and active and t > prev:
            share = (t - prev) / len(active)
            mapped_union += t - prev
            for r, c in active.values():
                regions[r] = regions.get(r, 0.0) + share
                categories[c] = categories.get(c, 0.0) + share
        prev = t
        if kind == 1:
            active[ti] = (region, cat)
        else:
            active.pop(ti, None)

    union_all = _union_us(all_intervals)
    return {
        "regions": {r: s / 1e6 / steps for r, s in regions.items()},
        "categories": {c: s / 1e6 / steps for c, s in categories.items()},
        "device_busy_s": union_all / 1e6 / steps,
        "mapped_union_s": mapped_union / 1e6 / steps,
        "orphan_s": max(0.0, union_all - mapped_union) / 1e6 / steps,
        "n_mapped": n_mapped,
        "n_unmapped": n_orphan,
        "steps": steps,
    }


# -------------------------------------------------------------------- ledger
#: serialized-ledger schema (validated by tests and the report tool)
MFU_LEDGER_KEYS = ("schema_version", "step_s", "device_busy_s", "host_s",
                   "orphan_s", "model_flops", "peak_flops", "achieved_mfu",
                   "roofline_mfu", "waterfall", "regions", "top_sinks",
                   "reconciliation", "truncated_trace", "device")


def ledger(roofline: Optional[Dict[str, Any]],
           measured: Dict[str, Any],
           step_s: float,
           truncated_trace: bool = False) -> Dict[str, Any]:
    """The join: analytic roofline table + measured per-region times + the
    measured clean-step wall → the MFU ledger.

    ``roofline`` is ``analysis/roofline.py``'s serialized table
    (``{"device", "spec": {"peak_flops", ...}, "regions": {r: {"flops",
    "hbm_bytes", "comm_bytes", "achievable_s", "bound_by"}},
    "total_flops", "total_achievable_s"}``) — optional: without it the
    ledger is measured-only (no waterfall/verdicts), which is what a bare
    trace on a login node can still say.

    Waterfall semantics: ``hardware_peak`` is the time the step's analytic
    FLOPs would take at 100% MFU; ``roofline_achievable`` adds each
    region's binding resource (compute, HBM bytes, or comm bytes — the
    per-region max, summed, an optimistic no-overlap-needed floor);
    ``measured`` is the observed clean-step wall. Each level carries the
    MFU the step WOULD run at if time stopped there, so gap = distance
    between adjacent bars and names whether the model (peak→roofline) or
    the execution (roofline→measured) loses the time.

    Reconciliation: region times (``host`` = step wall − device-busy union,
    included) must re-sum to the step wall. Region attribution is
    wall-exact (``measure_regions``), so the frac moves away from 1.0 for
    exactly two reasons: ORPHANED op time (measured ops whose name the
    opmap doesn't know — a typo'd scope, a stale opmap) pushes it low, and
    a window that measured MORE than the claimed step (two steps fused,
    wrong window) pushes it high."""
    step_s = max(float(step_s), 1e-12)
    meas_regions = dict(measured.get("regions", {}))
    device_busy = float(measured.get("device_busy_s", 0.0))
    host_s = max(0.0, step_s - device_busy)
    meas_regions["host"] = host_s
    spec = (roofline or {}).get("spec", {})
    peak = float(spec.get("peak_flops", 0.0))
    total_flops = float((roofline or {}).get("total_flops", 0.0))
    roof_regions = (roofline or {}).get("regions", {})
    roof_total_s = float((roofline or {}).get("total_achievable_s", 0.0))

    regions_out: Dict[str, Dict[str, Any]] = {}
    for name in sorted(set(meas_regions) | set(roof_regions)):
        meas = float(meas_regions.get(name, 0.0))
        roof = roof_regions.get(name, {})
        achievable = float(roof.get("achievable_s", 0.0))
        regions_out[name] = {
            "measured_s": meas,
            "frac": meas / step_s,
            "achievable_s": achievable,
            # measured/achievable: how far this region runs above its own
            # roofline floor (1.0 = at the roofline; 50 = 50x headroom)
            "headroom": (meas / achievable) if achievable > 0 else None,
            "bound_by": roof.get("bound_by"),
            "flops": float(roof.get("flops", 0.0)),
            "hbm_bytes": float(roof.get("hbm_bytes", 0.0)),
            "comm_bytes": float(roof.get("comm_bytes", 0.0)),
        }

    achieved_mfu = (total_flops / (step_s * peak)) if peak > 0 else None
    roofline_mfu = (total_flops / (roof_total_s * peak)
                    if peak > 0 and roof_total_s > 0 else None)
    waterfall = []
    if peak > 0 and total_flops > 0:
        peak_s = total_flops / peak
        waterfall = [
            {"level": "hardware_peak", "s": peak_s, "mfu": 1.0},
            {"level": "roofline_achievable", "s": roof_total_s,
             "mfu": roofline_mfu},
            {"level": "measured", "s": step_s, "mfu": achieved_mfu},
        ]
    sinks = sorted((r for r in regions_out if r != "host"),
                   key=lambda r: -regions_out[r]["measured_s"])
    region_sum = sum(v["measured_s"] for v in regions_out.values())
    return {
        "schema_version": 1,
        "step_s": step_s,
        "device_busy_s": device_busy,
        "host_s": host_s,
        "orphan_s": float(measured.get("orphan_s", 0.0)),
        "model_flops": total_flops,
        "peak_flops": peak,
        "achieved_mfu": achieved_mfu,
        "roofline_mfu": roofline_mfu,
        "waterfall": waterfall,
        "regions": regions_out,
        "top_sinks": sinks[:5],
        "reconciliation": {"region_sum_s": region_sum, "step_s": step_s,
                           "frac": region_sum / step_s},
        "truncated_trace": bool(truncated_trace),
        "device": (roofline or {}).get("device"),
        "categories": dict(measured.get("categories", {})),
    }


def validate_ledger(d: Dict[str, Any]) -> List[str]:
    """Missing-key check against :data:`MFU_LEDGER_KEYS` (schema v1)."""
    return [k for k in MFU_LEDGER_KEYS if k not in d]


def ledger_events(led: Dict[str, Any], step: int = 0
                  ) -> List[Tuple[str, Any, int]]:
    """Strict-registry ``MFU/*`` scalar events from a ledger (dot-tail
    region members — ``MFU/region.attn`` — so the static event-name lint
    resolves every literal)."""
    ev: List[Tuple[str, Any, int]] = [
        ("MFU/step_s", led["step_s"], step),
        ("MFU/device_busy_s", led["device_busy_s"], step),
    ]
    if led.get("achieved_mfu") is not None:
        ev.append(("MFU/achieved", led["achieved_mfu"], step))
    if led.get("roofline_mfu") is not None:
        ev.append(("MFU/roofline_bound", led["roofline_mfu"], step))
    if led.get("model_flops"):
        ev.append(("MFU/model_tflops", led["model_flops"] / 1e12, step))
    for name in REGIONS:
        r = led["regions"].get(name)
        if r is not None:
            # members enumerated from REGIONS, each declared exactly in
            # EVENT_NAMES — the base below never ships a typo'd member
            ev.append((f"MFU/region.{name}",  # dslint: allow(undeclared-event-name) registry-enumerated member builder
                       r["measured_s"], step))
    return ev


# -------------------------------------------------------------------- render
def _fmt_s(sec: Optional[float]) -> str:
    if sec is None:
        return "     -"
    if sec < 1e-3:
        return f"{sec * 1e6:.0f}us"
    return f"{sec * 1000:.1f}ms" if sec < 1.0 else f"{sec:.2f}s"


def _fmt_pct(x: Optional[float]) -> str:
    return "    -" if x is None else f"{100.0 * x:5.1f}%"


def render_ledger(led: Dict[str, Any], top: int = 10) -> str:
    """Human-readable ledger: waterfall, per-region table, top sinks."""
    lines = ["MFU ledger" + (f" — device {led['device']}"
                             if led.get("device") else "")]
    if led.get("truncated_trace"):
        lines.append("  WARNING: trace window was truncated — measured "
                     "times are a lower bound")
    if led.get("achieved_mfu") is not None:
        lines.append(f"  achieved MFU: {_fmt_pct(led['achieved_mfu'])} "
                     f"({led['model_flops'] / 1e12:.3f} TFLOP analytic step "
                     f"in {_fmt_s(led['step_s'])})")
    if led.get("waterfall"):
        lines.append("  gap waterfall (where would the step be if time "
                     "stopped at each level):")
        for w in led["waterfall"]:
            lines.append(f"    {w['level']:<22}{_fmt_s(w['s']):>10}  "
                         f"MFU {_fmt_pct(w.get('mfu'))}")
    regions = led.get("regions", {})
    if regions:
        lines.append(f"  {'region':<12}{'measured':>10}{'share':>8}"
                     f"{'roofline':>10}{'headroom':>10}  bound by")
        order = sorted(regions, key=lambda r: -regions[r]["measured_s"])
        for name in order:
            r = regions[name]
            if r["measured_s"] <= 0 and r["achievable_s"] <= 0:
                continue
            head = (f"{r['headroom']:8.1f}x" if r.get("headroom")
                    else "       -")
            lines.append(
                f"  {name:<12}{_fmt_s(r['measured_s']):>10}"
                f"{_fmt_pct(r['frac']):>8}{_fmt_s(r['achievable_s']):>10}"
                f"{head:>10}  {r.get('bound_by') or '-'}")
    sinks = led.get("top_sinks", [])[:top]
    if sinks:
        lines.append("  top sinks: " + ", ".join(
            f"{s} ({_fmt_s(regions[s]['measured_s'])})" for s in sinks))
    rec = led.get("reconciliation", {})
    if rec:
        frac = rec.get("frac", 0.0)
        flag = "" if abs(frac - 1.0) <= 0.05 else \
            "  <-- regions do not re-sum to the step (orphaned ops or " \
            "wrong window)"
        lines.append(f"  reconciliation: region sum "
                     f"{_fmt_s(rec.get('region_sum_s'))} vs step "
                     f"{_fmt_s(rec.get('step_s'))} "
                     f"({_fmt_pct(frac)} accounted){flag}")
        if led.get("orphan_s"):
            lines.append(f"  orphaned op time (not in opmap): "
                         f"{_fmt_s(led['orphan_s'])}")
    cats = led.get("categories", {})
    if cats:
        order = sorted(cats, key=lambda c: -cats[c])
        lines.append("  by HLO category: " + ", ".join(
            f"{c}={_fmt_s(cats[c])}" for c in order if cats[c] > 0))
    return "\n".join(lines)
