"""Experiment monitors.

Analog of ``deepspeed/monitor/`` — ``Monitor`` ABC + TensorBoard/W&B/CSV backends
(``monitor/{monitor,tensorboard,wandb,csv_monitor}.py``, config ``monitor/config.py``).
Same event contract: ``write_events([(name, value, global_step), ...])``.
"""
import csv
import os
import threading
from typing import TYPE_CHECKING, Any, Dict, List, Optional, Tuple

from ..utils.logging import logger

if TYPE_CHECKING:  # import-time would cycle: runtime/__init__ -> engine ->
    from ..runtime.config import MonitorConfig  # monitor -> runtime.config

Event = Tuple[str, Any, int]


class ResilienceCounters:
    """Process-wide degradation counters (ISSUE: operators must *see* retries,
    fallback loads, emergency saves and restarts instead of discovering them
    at recovery time). Incremented by the checkpoint writers, the preemption
    handler and the elastic agent; the engine surfaces changed counters as
    ``Resilience/*`` monitor events at its print boundaries."""

    NAMES = ("io_retries", "io_giveups", "corrupt_tags_skipped",
             "fallback_loads", "emergency_saves", "preemptions",
             "staging_sweeps", "staging_promotions", "checkpoints_rotated",
             "restarts")

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = dict.fromkeys(self.NAMES, 0)

    def incr(self, name: str, n: int = 1) -> int:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n
            return self._counts[name]

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._counts = dict.fromkeys(self.NAMES, 0)


resilience_counters = ResilienceCounters()


class Monitor:
    def __init__(self, config: "MonitorConfig"):
        self.config = config
        self.enabled = True

    def write_events(self, events: List[Event]) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class CsvMonitor(Monitor):
    """CSV backend (reference: ``monitor/csv_monitor.py``): one file per metric."""

    def __init__(self, config: "MonitorConfig"):
        super().__init__(config)
        self.base = os.path.join(config.csv_output_path or "csv_logs",
                                 config.csv_job_name)
        os.makedirs(self.base, exist_ok=True)
        self._files = {}

    def _writer(self, name: str):
        if name not in self._files:
            path = os.path.join(self.base, name.replace("/", "_") + ".csv")
            f = open(path, "a", newline="")
            self._files[name] = (f, csv.writer(f))
        return self._files[name]

    def write_events(self, events: List[Event]) -> None:
        for name, value, step in events:
            f, w = self._writer(name)
            w.writerow([step, float(value)])

    def flush(self) -> None:
        for f, _ in self._files.values():
            f.flush()

    def close(self) -> None:
        for f, _ in self._files.values():
            f.close()
        self._files.clear()


class TensorBoardMonitor(Monitor):
    """TensorBoard backend (reference: ``monitor/tensorboard.py``); degrades to a
    warning when no tensorboard writer is importable in the image."""

    def __init__(self, config: "MonitorConfig"):
        super().__init__(config)
        self.writer = None
        path = os.path.join(config.tensorboard_output_path or "tensorboard",
                            config.tensorboard_job_name)
        try:
            from torch.utils.tensorboard import SummaryWriter  # type: ignore

            self.writer = SummaryWriter(log_dir=path)
        except Exception as e:  # pragma: no cover - env dependent
            logger.warning("tensorboard unavailable (%s); events dropped", e)
            self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if not self.writer:
            return
        for name, value, step in events:
            self.writer.add_scalar(name, float(value), step)

    def flush(self) -> None:
        if self.writer:
            self.writer.flush()

    def close(self) -> None:
        if self.writer:
            self.writer.close()


class WandbMonitor(Monitor):
    """Weights & Biases backend (reference: ``monitor/wandb.py``); gated on import."""

    def __init__(self, config: "MonitorConfig"):
        super().__init__(config)
        try:
            import wandb  # type: ignore

            wandb.init(project=config.wandb_project, entity=config.wandb_team,
                       group=config.wandb_group)
            self._wandb = wandb
        except Exception as e:  # pragma: no cover - env dependent
            logger.warning("wandb unavailable (%s); events dropped", e)
            self._wandb = None
            self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if not self._wandb:
            return
        for name, value, step in events:
            self._wandb.log({name: float(value)}, step=step)


class MonitorMaster(Monitor):
    """Fan-out to all enabled backends; only process rank 0 writes (reference:
    ``monitor/monitor.py`` MonitorMaster rank gating)."""

    def __init__(self, config: "MonitorConfig"):
        super().__init__(config)
        import jax

        self.monitors: List[Monitor] = []
        if jax.process_index() == 0:
            if config.tensorboard_enabled:
                self.monitors.append(TensorBoardMonitor(config))
            if config.wandb_enabled:
                self.monitors.append(WandbMonitor(config))
            if config.csv_enabled:
                self.monitors.append(CsvMonitor(config))
        self.enabled = any(m.enabled for m in self.monitors)

    def write_events(self, events: List[Event]) -> None:
        for m in self.monitors:
            if m.enabled:
                m.write_events(events)

    def flush(self) -> None:
        for m in self.monitors:
            m.flush()

    def close(self) -> None:
        for m in self.monitors:
            m.close()
