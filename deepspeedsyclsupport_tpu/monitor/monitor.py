"""Experiment monitors.

Analog of ``deepspeed/monitor/`` — ``Monitor`` ABC + TensorBoard/W&B/CSV backends
(``monitor/{monitor,tensorboard,wandb,csv_monitor}.py``, config ``monitor/config.py``).
Same event contract: ``write_events([(name, value, global_step), ...])``.

This layer now sits on the structured observability spine
(:mod:`.telemetry`): event names are validated against the ``Group/name``
registry before fan-out, and the :class:`JsonlMonitor` backend writes a
rank-local JSONL stream shared with the flight recorder, so scalar metrics
and step spans land interleaved in one crash-surviving file.
"""
import csv
import os
import threading
import urllib.parse
from typing import TYPE_CHECKING, Any, Dict, List, Optional, Tuple

from ..utils.logging import logger
# ResilienceCounters moved to the telemetry spine; re-exported here because
# the checkpoint writers / fault injection / elastic agent import them from
# this module.
from .telemetry import (ResilienceCounters, check_events,  # noqa: F401
                        resilience_counters)

if TYPE_CHECKING:  # import-time would cycle: runtime/__init__ -> engine ->
    from ..runtime.config import MonitorConfig  # monitor -> runtime.config

Event = Tuple[str, Any, int]


class Monitor:
    def __init__(self, config: Optional["MonitorConfig"] = None):
        self.config = config
        self.enabled = True

    def write_events(self, events: List[Event]) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


def csv_filename_for_event(name: str) -> str:
    """Reversible metric-name → filename mapping. The old ``replace('/', '_')``
    collapsed ``a/b`` and ``a_b`` onto one file; percent-encoding keeps every
    distinct event name on a distinct file and :func:`event_for_csv_filename`
    inverts it exactly."""
    return urllib.parse.quote(name, safe="") + ".csv"


def event_for_csv_filename(fname: str) -> str:
    base = fname[:-4] if fname.endswith(".csv") else fname
    return urllib.parse.unquote(base)


class CsvMonitor(Monitor):
    """CSV backend (reference: ``monitor/csv_monitor.py``): one file per metric.

    Hardening over the reference port: reversible file naming (no more
    ``a/b`` vs ``a_b`` collisions), non-numeric event values are skipped
    with a warning instead of raising mid-flush, and files are flushed every
    ``flush_interval`` write batches instead of only at ``close()`` — a
    preempted run keeps its metrics."""

    def __init__(self, config: "MonitorConfig"):
        super().__init__(config)
        self.base = os.path.join(config.csv_output_path or "csv_logs",
                                 config.csv_job_name)
        os.makedirs(self.base, exist_ok=True)
        self.flush_interval = max(1, int(
            getattr(config, "csv_flush_interval", 10)))
        self._files = {}
        self._writes_since_flush = 0
        self._warned_bad_values = set()

    def _writer(self, name: str):
        if name not in self._files:
            path = os.path.join(self.base, csv_filename_for_event(name))
            f = open(path, "a", newline="")
            self._files[name] = (f, csv.writer(f))
        return self._files[name]

    def write_events(self, events: List[Event]) -> None:
        for name, value, step in events:
            try:
                value = float(value)
            except (TypeError, ValueError):
                if name not in self._warned_bad_values:
                    self._warned_bad_values.add(name)
                    logger.warning(
                        "CsvMonitor: non-numeric value %r for event %r; "
                        "skipped (further occurrences silenced)", value, name)
                continue
            f, w = self._writer(name)
            w.writerow([step, value])
        self._writes_since_flush += 1
        if self._writes_since_flush >= self.flush_interval:
            self.flush()

    def flush(self) -> None:
        self._writes_since_flush = 0
        for f, _ in self._files.values():
            f.flush()

    def close(self) -> None:
        for f, _ in self._files.values():
            f.close()
        self._files.clear()


class JsonlMonitor(Monitor):
    """Rank-local structured JSONL backend — the flight recorder's disk sink.

    Unlike the scalar backends this one exists on EVERY rank (per-host
    telemetry is the point: stragglers and preemptions are per-host
    phenomena). Scalar events become ``{"kind": "metric", ...}`` lines;
    flight-recorder records (spans, compile events, memory samples, dump
    markers) are appended through :meth:`write_record` interleaved in arrival
    order. Lines are buffered and flushed every ``flush_interval`` records —
    ``dump()``/``flush()`` force-drains, which is what the preemption handler
    relies on."""

    def __init__(self, config: Optional["MonitorConfig"] = None,
                 path: Optional[str] = None, flush_interval: int = 64):
        super().__init__(config)
        if path is None:
            if config is None or not getattr(config, "jsonl_enabled", False):
                raise ValueError("JsonlMonitor needs a path or a config with "
                                 "jsonl_enabled")
            import jax

            path = os.path.join(
                config.jsonl_output_path or "telemetry_logs",
                config.jsonl_job_name,
                f"flightrec_rank{jax.process_index()}.jsonl")
            flush_interval = getattr(config, "jsonl_flush_interval",
                                     flush_interval)
        self.path = path
        self.flush_interval = max(1, int(flush_interval))
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._buf: List[str] = []
        self._file = None
        self._recorder = None

    def attach_recorder(self, recorder) -> None:
        """Become the flight recorder's sink; subsequent scalar events are
        routed THROUGH the recorder (one ring, one stream) instead of being
        written directly."""
        if self._recorder is recorder:
            return
        self._recorder = recorder
        recorder.add_sink(self.write_record, flush=self.flush)

    # --------------------------------------------------------------- writing
    def write_events(self, events: List[Event]) -> None:
        if self._recorder is not None:
            for name, value, step in events:
                self._recorder.record("metric", name, step=step,
                                      value=_jsonable_value(value))
            return
        for name, value, step in events:
            self.write_record({"kind": "metric", "name": name,
                               "step": step,
                               "value": _jsonable_value(value)})

    def write_record(self, rec: Dict[str, Any]) -> None:
        import json

        try:
            line = json.dumps(rec, default=_json_default)
        except (TypeError, ValueError) as e:
            logger.warning("JsonlMonitor: unserializable record %r (%s); "
                           "skipped", rec.get("name"), e)
            return
        with self._lock:
            self._buf.append(line)
            should_flush = len(self._buf) >= self.flush_interval
        if should_flush:
            self.flush()

    def flush(self) -> None:
        with self._lock:
            if not self._buf:
                return
            lines, self._buf = self._buf, []
            if self._file is None:
                self._file = open(self.path, "a")
            self._file.write("\n".join(lines) + "\n")
            self._file.flush()

    def close(self) -> None:
        self.flush()
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


def _jsonable_value(value: Any) -> Any:
    """Scalar-ify device arrays / numpy scalars for JSON."""
    try:
        import json

        json.dumps(value)
        return value
    except (TypeError, ValueError):
        try:
            return float(value)
        except (TypeError, ValueError):
            return repr(value)


def _json_default(obj: Any) -> Any:
    try:
        return float(obj)
    except (TypeError, ValueError):
        return repr(obj)


class TensorBoardMonitor(Monitor):
    """TensorBoard backend (reference: ``monitor/tensorboard.py``); degrades to a
    warning when no tensorboard writer is importable in the image."""

    def __init__(self, config: "MonitorConfig"):
        super().__init__(config)
        self.writer = None
        path = os.path.join(config.tensorboard_output_path or "tensorboard",
                            config.tensorboard_job_name)
        try:
            from torch.utils.tensorboard import SummaryWriter  # type: ignore

            self.writer = SummaryWriter(log_dir=path)
        except Exception as e:  # pragma: no cover - env dependent
            logger.warning("tensorboard unavailable (%s); events dropped", e)
            self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if not self.writer:
            return
        for name, value, step in events:
            self.writer.add_scalar(name, float(value), step)

    def flush(self) -> None:
        if self.writer:
            self.writer.flush()

    def close(self) -> None:
        if self.writer:
            self.writer.close()


class WandbMonitor(Monitor):
    """Weights & Biases backend (reference: ``monitor/wandb.py``); gated on import."""

    def __init__(self, config: "MonitorConfig"):
        super().__init__(config)
        try:
            import wandb  # type: ignore

            wandb.init(project=config.wandb_project, entity=config.wandb_team,
                       group=config.wandb_group)
            self._wandb = wandb
        except Exception as e:  # pragma: no cover - env dependent
            logger.warning("wandb unavailable (%s); events dropped", e)
            self._wandb = None
            self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if not self._wandb:
            return
        for name, value, step in events:
            self._wandb.log({name: float(value)}, step=step)


class MonitorMaster(Monitor):
    """Fan-out to all enabled backends; only process rank 0 writes the scalar
    backends (reference: ``monitor/monitor.py`` MonitorMaster rank gating),
    while the JSONL flight-recorder backend is rank-LOCAL by design.

    Every event batch is validated against the telemetry event-name registry
    first: names must match ``Group/name`` and be declared
    (``monitor/telemetry.py`` ``EVENT_NAMES``/``EVENT_PREFIXES``). Under
    strict mode (``DSTPU_STRICT_EVENTS=1`` — on in the test suite) an
    undeclared name raises; otherwise it warns once and passes through."""

    def __init__(self, config: "MonitorConfig"):
        super().__init__(config)
        import jax

        self.monitors: List[Monitor] = []
        if jax.process_index() == 0:
            if config.tensorboard_enabled:
                self.monitors.append(TensorBoardMonitor(config))
            if config.wandb_enabled:
                self.monitors.append(WandbMonitor(config))
            if config.csv_enabled:
                self.monitors.append(CsvMonitor(config))
        if getattr(config, "jsonl_enabled", False):
            self.monitors.append(JsonlMonitor(config))
        self.enabled = any(m.enabled for m in self.monitors)

    def write_events(self, events: List[Event]) -> None:
        events = check_events(events)
        for m in self.monitors:
            if m.enabled:
                m.write_events(events)

    def flush(self) -> None:
        for m in self.monitors:
            m.flush()

    def close(self) -> None:
        for m in self.monitors:
            m.close()
