from .monitor import (CsvMonitor, JsonlMonitor, Monitor, MonitorMaster,
                      ResilienceCounters, TensorBoardMonitor, WandbMonitor,
                      csv_filename_for_event, event_for_csv_filename,
                      resilience_counters)
from .pod import (PodReport, RankStream, discover_rank_files, fuse_pod,
                  load_rank_streams, pod_report_from_paths,
                  validate_pod_report)
from .telemetry import (EVENT_NAME_RE, EVENT_NAMES, EVENT_PREFIXES,
                        FlightRecorder, GoodputAccounter, Heartbeat,
                        MetricsRegistry, Telemetry, UndeclaredEventError,
                        build_telemetry, check_events, declare_events,
                        is_declared, metrics_registry)
