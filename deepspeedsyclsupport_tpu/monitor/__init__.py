from .monitor import (CsvMonitor, Monitor, MonitorMaster, ResilienceCounters,
                      TensorBoardMonitor, WandbMonitor, resilience_counters)
