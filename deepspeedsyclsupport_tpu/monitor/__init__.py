from .monitor import (CsvMonitor, JsonlMonitor, Monitor, MonitorMaster,
                      ResilienceCounters, TensorBoardMonitor, WandbMonitor,
                      csv_filename_for_event, event_for_csv_filename,
                      resilience_counters)
from .telemetry import (EVENT_NAME_RE, EVENT_NAMES, EVENT_PREFIXES,
                        FlightRecorder, GoodputAccounter, Heartbeat,
                        MetricsRegistry, Telemetry, UndeclaredEventError,
                        build_telemetry, check_events, declare_events,
                        is_declared, metrics_registry)
