"""Structured observability spine: metrics registry + flight recorder + goodput.

The repo grew four disconnected telemetry islands — wall-clock timers
(``utils/timer.py``), trace-time comms accounting (``comm/comms_logging.py``),
static FLOPS profiling (``profiling/flops_profiler.py``) and the resilience
counters — none of which left an on-disk record that survives a crash. This
module is the shared spine they are re-pointed at:

* :class:`MetricsRegistry` — process-wide counters / gauges / fixed-bucket
  histograms, cheap enough for the step hot path.
* :class:`FlightRecorder` — a bounded in-memory ring of structured records
  (step spans, compile events, memory samples, checkpoint spans, metric
  writes) that streams to a rank-local JSONL sink and is force-dumped on
  crash/SIGTERM, so the last N steps before any death are always on disk.
* :class:`GoodputAccounter` — attributes wall-clock to productive step time
  vs. checkpoint, compile, startup and residual overhead; the ``Goodput/*``
  events answer "what fraction of wall-clock was productive training?".
* recompile detection — a ``jax.monitoring`` listener counting jit cache
  misses and their wall-time, so a shape-thrash loop shows up as
  ``Compile/*`` events with the offending arg-shape diff attached.
* :class:`Heartbeat` — a per-rank freshness file the elastic agent watches to
  tell hung steps from slow steps (stale heartbeat → ``faulthandler`` stack
  dump before restart).
* the **event-name registry** — every scalar event emitted through
  ``MonitorMaster`` must match the ``Group/name`` convention and be declared
  here (exact name or family prefix); a typo'd metric name fails tests
  instead of silently forking a new CSV file.

``tools/trace_report.py`` renders the JSONL stream offline into a step
timeline / goodput / straggler summary. Format: one JSON object per line,
``{"seq", "t", "kind", "name", "step", "dur", "value", "data"}`` with absent
fields omitted; ``kind`` ∈ meta | span | event | metric | gauge | counter |
goodput | dump.

No module-level imports from sibling packages (``monitor.monitor`` imports
this module; everything else here is imported lazily to keep the dependency
graph acyclic).
"""
import contextlib
import json
import os
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ..utils.logging import logger
# shared fixed-bucket helpers live in the stdlib-only pod module (the
# offline CLIs load THAT file standalone on jax-less nodes, so the import
# must point this way — pod never imports telemetry)
from .pod import DURATION_BUCKETS_S, histogram_quantile  # noqa: F401
# region registry for the MFU/* event family lives in the stdlib-only mfu
# module (same direction as the pod import above: the offline CLIs load
# THAT file standalone — mfu.py never imports telemetry)
from .mfu import REGIONS as MFU_REGIONS
# request-lifecycle stage registry for the Serve/stage.* / Fleet/stage.*
# families lives in the stdlib-only reqtrace module (same import direction:
# tools/trace_report.py loads THAT file standalone on jax-less nodes)
from .reqtrace import (FLEET_STAGES as REQTRACE_FLEET_STAGES,
                       SERVE_STAGES as REQTRACE_SERVE_STAGES,
                       STAGE_HISTOGRAMS as REQTRACE_STAGE_HISTOGRAMS)

Event = Tuple[str, Any, int]

# =========================================================================
# Resilience counters (moved here from monitor/monitor.py — the degradation
# counters are one island this module unifies; monitor.py re-exports them
# for backwards compatibility).
# =========================================================================


class ResilienceCounters:
    """Process-wide degradation counters (operators must *see* retries,
    fallback loads, emergency saves and restarts instead of discovering them
    at recovery time). Incremented by the checkpoint writers, the preemption
    handler and the elastic agent; the engine surfaces changed counters as
    ``Resilience/*`` monitor events at its print boundaries."""

    NAMES = ("io_retries", "io_giveups", "corrupt_tags_skipped",
             "fallback_loads", "emergency_saves", "preemptions",
             "staging_sweeps", "staging_promotions", "checkpoints_rotated",
             "restarts", "hang_restarts",
             # pod fault tolerance (PR 9): two-phase commit protocol,
             # collective-hang watchdog (rc 218) and the elastic agent's
             # prompt sibling teardown — per-cause, so operators can tell a
             # flaky interconnect from a preemption storm at a glance
             "pod_commits", "torn_pod_quarantined", "comm_hang_aborts",
             "comm_hang_restarts", "pod_teardowns",
             # serving-plane fault tolerance (PR 11): the stuck-decode
             # watchdog's rc-219 aborts and the supervisor's per-cause
             # restart class for them (inference/v2/supervisor.py)
             "serve_hang_aborts", "serve_hang_restarts",
             # training-health sentinel (runtime/sentinel.py): batches whose
             # update the sentinel discarded (spike/NaN gate or fp16
             # overflow — one unified ledger), rollbacks to the promoted
             # last-good tag, and the elastic agent's per-cause restart
             # class for rc-220 divergence aborts
             "skipped_batches", "rollbacks", "divergence_restarts")

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = dict.fromkeys(self.NAMES, 0)

    def incr(self, name: str, n: int = 1) -> int:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n
            return self._counts[name]

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._counts = dict.fromkeys(self.NAMES, 0)


resilience_counters = ResilienceCounters()

# =========================================================================
# Event-name registry
# =========================================================================

#: ``Group/name`` convention: slash-separated segments of word chars / dots /
#: dashes, at least two segments. ``Train/Samples/train_loss`` ✓, ``loss`` ✗.
EVENT_NAME_RE = re.compile(r"^[A-Za-z0-9][\w.\-]*(/[\w.\-]+)+$")

#: Exact declared event names. Anything the engine emits through
#: ``MonitorMaster`` must appear here (or match a family prefix below) —
#: the tier-1 guard test runs with strict mode on, so a typo'd name raises
#: instead of silently forking a new CSV file.
EVENT_NAMES = frozenset(
    {"Train/Samples/train_loss", "Train/Samples/lr",
     "Train/Samples/loss_scale",
     "Goodput/productive_s", "Goodput/checkpoint_s", "Goodput/compile_s",
     "Goodput/offload_stall_s", "Goodput/rollback_s", "Goodput/startup_s",
     "Goodput/other_s", "Goodput/total_s", "Goodput/productive_frac",
     # hierarchical offload pipeline (runtime/multihost_offload.py +
     # offload_pipeline.py; docs/offload.md): per-direction bytes and
     # effective bandwidth, host fp32-Adam seconds, exposed transfer
     # stall, and the derived overlap efficiency (1 − exposed/total)
     "Offload/d2h_bytes", "Offload/h2d_bytes", "Offload/nvme_read_bytes",
     "Offload/nvme_write_bytes", "Offload/d2h_gbps", "Offload/h2d_gbps",
     "Offload/nvme_read_gbps", "Offload/host_compute_s", "Offload/stall_s",
     "Offload/overlap_efficiency",
     "Memory/bytes_in_use", "Memory/peak_bytes_in_use",
     "Compile/count", "Compile/total_s",
     "Ckpt/save_s", "Ckpt/bytes_written",
     # two-phase all-ranks commit (checkpoint/engine.py::pod_commit):
     # cumulative seconds spent in phase-1 manifest writes + the
     # cross-process barrier + the rank-0 commit-record write
     "Ckpt/pod_commit_s",
     # SLA serving policy (inference/v2/serving.py — admission gate,
     # slack scheduler, KV-pressure eviction; docs/serving.md): queue
     # depth / KV-pool occupancy / live-stream gauges, admission outcome
     # counters, and TTFT/ITL latency histograms
     "Serve/queue_depth", "Serve/kv_occupancy", "Serve/live_seqs",
     "Serve/admitted", "Serve/queued", "Serve/shed", "Serve/evicted",
     "Serve/completed", "Serve/ttft_s", "Serve/itl_s",
     # serving-plane recovery (inference/v2/supervisor.py — request
     # journal replay after an engine crash, stuck-decode rc-219 aborts;
     # dot-tail convention like Pod/comm_hang.* so the static event-name
     # lint resolves literals): counters + the time-to-recover histogram
     "Serve/recovery.replays", "Serve/recovery.replay_sheds",
     "Serve/recovery.serve_hang_aborts",
     "Serve/recovery.time_to_recover_s",
     # cross-request KV prefix cache (inference/v2/prefix_cache.py;
     # docs/serving.md "prefix reuse", semantics in docs/observability.md):
     # admission-probe hit/miss counters, prefill tokens skipped, physical
     # blocks mapped into more than one block table, copy-on-write
     # unshares, plus the hit-ratio / pinned-block gauges
     "Serve/prefix.hits", "Serve/prefix.misses",
     "Serve/prefix.tokens_saved", "Serve/prefix.blocks_shared",
     "Serve/prefix.cow_copies", "Serve/prefix.hit_ratio",
     "Serve/prefix.pinned_blocks",
     # serving fleet control plane (inference/v2/fleet — router edge
     # admission, affinity placement, journal-based cross-replica
     # failover; docs/serving.md "fleet control plane"): routed/shed/
     # completed counters, failover accounting, rotation gauges and the
     # routed-TTFT histogram. Per-replica members (live/queued per
     # replica id) are data-dependent and ride the Fleet/replica. prefix.
     "Fleet/routed", "Fleet/shed", "Fleet/completed", "Fleet/affinity_hits",
     "Fleet/failover.deaths", "Fleet/failover.replays",
     "Fleet/failover.replay_sheds",
     "Fleet/replicas_ready", "Fleet/inflight", "Fleet/routed_ttft_s",
     # MFU ledger (monitor/mfu.py + analysis/roofline.py; docs/
     # observability.md "MFU ledger"): achieved MFU vs the roofline bound,
     # the measured clean-step wall + device-busy split, and analytic step
     # FLOPs. Per-region measured seconds ride the dot-tail convention
     # (MFU/region.attn) and are enumerated from the region registry below
     # so the static event-name lint resolves every literal — a typo'd
     # region name fails dslint, not strict mode at runtime.
     "MFU/achieved", "MFU/roofline_bound", "MFU/step_s",
     "MFU/device_busy_s", "MFU/model_tflops",
     # training-health sentinel (runtime/sentinel.py; docs/resilience.md
     # "numerical faults"): robust z-scores of the loss / global grad-norm
     # history, the run-cumulative nonfinite-gradient element count, ladder
     # action counts (warn → skip → rollback → abort) and the current
     # anomaly streak. The per-region grad-norm breakdown is named to the
     # SAME region registry the MFU ledger uses, enumerated below so the
     # static event-name lint resolves every member.
     "Health/loss_z", "Health/grad_norm_z", "Health/nonfinite_count",
     "Health/warns", "Health/skips", "Health/rollbacks", "Health/aborts",
     "Health/anomaly_streak",
     # request-time attribution (monitor/reqtrace.py; docs/observability.md
     # "request-time attribution"): the admission→first-prefill-dispatch
     # queue-wait histogram and the sliding-window SLO burn gauges — the
     # fraction of first tokens missing their per-request TTFT SLA, the
     # fraction of arrivals shed, and miss_frac/error_budget burn rates.
     # Per-stage counters/histograms are enumerated from the reqtrace stage
     # registry below (the MFU-region pattern: a typo'd stage fails dslint's
     # undeclared-stage-name rule, not strict mode at runtime).
     "Serve/queue_wait_s",
     "Serve/slo.ttft_miss_frac", "Serve/slo.shed_frac", "Serve/slo.burn_rate",
     "Fleet/slo.ttft_miss_frac", "Fleet/slo.shed_frac", "Fleet/slo.burn_rate"}
    | {f"MFU/region.{r}" for r in MFU_REGIONS}  # dslint: allow(undeclared-event-name) registry-enumerated member builder
    | {f"Health/grad_norm.{r}" for r in MFU_REGIONS}  # dslint: allow(undeclared-event-name) registry-enumerated member builder
    | {f"Serve/stage.{s}" for s in REQTRACE_SERVE_STAGES}  # dslint: allow(undeclared-event-name) registry-enumerated member builder
    | {f"Fleet/stage.{s}" for s in REQTRACE_FLEET_STAGES}  # dslint: allow(undeclared-event-name) registry-enumerated member builder
    | {f"Serve/stage.{s}_s" for s in REQTRACE_STAGE_HISTOGRAMS}  # dslint: allow(undeclared-event-name) registry-enumerated member builder
    | {f"Serve/stage.{s}_s/{q}" for s in REQTRACE_STAGE_HISTOGRAMS  # dslint: allow(undeclared-event-name) registry-enumerated member builder
       for q in ("p50", "p95", "p99")}
    | {f"Serve/{h}/{q}" for h in ("ttft_s", "itl_s", "queue_wait_s",
                                  "recovery.time_to_recover_s")
       for q in ("p50", "p95", "p99")}
    | {f"Fleet/{h}/{q}" for h in ("routed_ttft_s",)
       for q in ("p50", "p95", "p99")}
    | {f"Resilience/{n}" for n in ResilienceCounters.NAMES})

#: Families whose member names are data-dependent (collective op mix, user
#: extensions, pod-scope aggregates whose per-class / per-rank member names
#: depend on the parallelism layout — see ``monitor/pod.py``; per-replica
#: fleet gauges keyed by replica id — ``inference/v2/fleet/router.py``). A
#: prefix declares the whole family.
EVENT_PREFIXES = ("Comm/", "Custom/", "Pod/", "Fleet/replica.")

_extra_event_names: set = set()
_warned_names: set = set()


class UndeclaredEventError(ValueError):
    """An event name violating the convention / registry under strict mode."""


def declare_events(names: Iterable[str]) -> None:
    """Register additional exact event names (user extensions). Names must
    already match the ``Group/name`` convention."""
    for name in names:
        if not EVENT_NAME_RE.match(name):
            raise UndeclaredEventError(
                f"event name {name!r} does not match the Group/name "
                f"convention ({EVENT_NAME_RE.pattern})")
        _extra_event_names.add(name)


def is_declared(name: str) -> bool:
    if not EVENT_NAME_RE.match(name):
        return False
    if name in EVENT_NAMES or name in _extra_event_names:
        return True
    return any(name.startswith(p) for p in EVENT_PREFIXES)


def events_strict() -> bool:
    """Strict mode: undeclared names raise instead of warn. On under pytest
    (tests/conftest.py sets ``DSTPU_STRICT_EVENTS=1``) and for any operator
    who exports it."""
    return os.environ.get("DSTPU_STRICT_EVENTS", "0").lower() in ("1", "true")


def check_events(events: List[Event]) -> List[Event]:
    """Validate event names against the registry. Strict mode raises
    :class:`UndeclaredEventError`; otherwise undeclared names warn once and
    pass through (operators keep their data, CI keeps its guard)."""
    for name, _value, _step in events:
        if is_declared(name):
            continue
        msg = (f"event name {name!r} is not declared in "
               f"monitor.telemetry.EVENT_NAMES / EVENT_PREFIXES (or violates "
               f"the Group/name convention); declare it via "
               f"declare_events([...])")
        if events_strict():
            raise UndeclaredEventError(msg)
        if name not in _warned_names:
            _warned_names.add(name)
            logger.warning(msg)
    return events


# =========================================================================
# Metrics registry
# =========================================================================


class Counter:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def incr(self, n: int = 1) -> int:
        with self._lock:
            self._value += n
            return self._value

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    __slots__ = ("name", "_value", "_t")

    def __init__(self, name: str):
        self.name = name
        self._value: float = 0.0
        self._t: float = 0.0

    def set(self, value: float) -> None:
        self._value = float(value)
        # wall timestamp (when was this gauge last set), not a duration
        self._t = time.time()  # dslint: allow(wall-clock-in-step-path)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram (Prometheus-style cumulative-le buckets)."""

    __slots__ = ("name", "buckets", "counts", "_sum", "_count", "_lock")

    def __init__(self, name: str, buckets: Tuple[float, ...] = DURATION_BUCKETS_S):
        self.name = name
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)  # +inf overflow bucket
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            for i, edge in enumerate(self.buckets):
                if value <= edge:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"buckets": list(self.buckets), "counts": list(self.counts),
                    "sum": self._sum, "count": self._count}

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (0 < q ≤ 1) from the fixed buckets: linear
        interpolation inside the bucket the target observation falls in.
        Resolution is the bucket width; an estimate landing in the +inf
        overflow bucket returns the highest finite edge (a floor, flagged by
        callers that care). ``None`` with no observations."""
        with self._lock:
            counts, total = list(self.counts), self._count
        return histogram_quantile(self.buckets, counts, total, q)

    def quantiles(self, qs: Iterable[float] = (0.5, 0.95, 0.99)
                  ) -> Dict[str, Optional[float]]:
        """{"p50": …, "p95": …, "p99": …} estimates (see :meth:`quantile`)."""
        return {f"p{int(round(q * 100))}": self.quantile(q) for q in qs}


class MetricsRegistry:
    """Process-wide named metrics. Creation is idempotent; the hot path is a
    dict lookup + a lock-free-ish update on the metric object itself."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            if name not in self._counters:
                self._counters[name] = Counter(name)
            return self._counters[name]

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            if name not in self._gauges:
                self._gauges[name] = Gauge(name)
            return self._gauges[name]

    def histogram(self, name: str,
                  buckets: Tuple[float, ...] = DURATION_BUCKETS_S) -> Histogram:
        with self._lock:
            if name not in self._histograms:
                self._histograms[name] = Histogram(name, buckets)
            return self._histograms[name]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counters": {n: c.value for n, c in self._counters.items()},
                "gauges": {n: g.value for n, g in self._gauges.items()},
                "histograms": {n: h.snapshot()
                               for n, h in self._histograms.items()},
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


#: Process-wide registry (the analog of ``resilience_counters`` for general
#: metrics; checkpoint writers and the engine feed it).
metrics_registry = MetricsRegistry()


# =========================================================================
# Flight recorder
# =========================================================================


class FlightRecorder:
    """Bounded ring of structured telemetry records.

    Every record is appended to an in-memory deque (``capacity`` newest
    records survive) and forwarded to any attached sinks (the rank-local
    JSONL writer). ``dump()`` force-flushes the sinks — wired into the
    preemption handler so the last steps before a SIGTERM are on disk."""

    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self._lock = threading.Lock()
        self._sinks: List[Tuple[Callable[[Dict[str, Any]], None],
                                Optional[Callable[[], None]]]] = []

    def add_sink(self, write_record: Callable[[Dict[str, Any]], None],
                 flush: Optional[Callable[[], None]] = None) -> None:
        """Register a per-record writer and (optionally) the flush that
        :meth:`dump` must call to force its buffer onto disk — explicit, so
        plain-function sinks don't silently lose their tail on a crash."""
        self._sinks.append((write_record, flush))

    # ------------------------------------------------------------- recording
    def record(self, kind: str, name: str, step: Optional[int] = None,
               dur: Optional[float] = None, value: Any = None,
               data: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        # "t" is an epoch timestamp for offline correlation across ranks —
        # wall clock by design; durations ("dur") come from perf_counter
        rec: Dict[str, Any] = {"kind": kind, "name": name,
                               "t": time.time()}  # dslint: allow(wall-clock-in-step-path)
        if step is not None:
            rec["step"] = int(step)
        if dur is not None:
            rec["dur"] = float(dur)
        if value is not None:
            rec["value"] = value
        if data:
            rec["data"] = data
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            sinks = tuple(self._sinks)
        for write, _flush in sinks:
            try:
                write(rec)
            except Exception as e:  # telemetry must never kill training
                logger.warning("flight-recorder sink failed: %s", e)
        return rec

    def event(self, name: str, step: Optional[int] = None, **data) -> Dict[str, Any]:
        return self.record("event", name, step=step, data=data or None)

    @contextlib.contextmanager
    def span(self, name: str, step: Optional[int] = None,
             data: Optional[Dict[str, Any]] = None):
        """Measure a region; the record lands on exit with its duration."""
        t0 = time.perf_counter()
        extra: Dict[str, Any] = dict(data or {})
        try:
            yield extra
        finally:
            self.record("span", name, step=step,
                        dur=time.perf_counter() - t0, data=extra or None)

    # ------------------------------------------------------------- inspection
    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        return len(self._ring)

    def dump(self, reason: str = "manual") -> List[Dict[str, Any]]:
        """Record a dump marker (with the metrics-registry snapshot inlined)
        and force-flush every sink. Returns the ring contents."""
        self.record("dump", "flight_recorder/dump",
                    data={"reason": reason,
                          "metrics": metrics_registry.snapshot(),
                          "resilience": resilience_counters.snapshot()})
        for _write, flush in tuple(self._sinks):
            if flush is None:
                continue
            try:
                flush()
            except Exception as e:
                logger.warning("flight-recorder dump flush failed: %s", e)
        return self.snapshot()


# Active recorder: the seam through which re-pointed islands
# (``utils/timer.py`` spans, checkpoint writers) reach the current engine's
# recorder without holding a reference. Last telemetry constructed wins.
_active_recorder: Optional[FlightRecorder] = None


def set_active_recorder(rec: Optional[FlightRecorder]) -> None:
    global _active_recorder
    _active_recorder = rec


def get_active_recorder() -> Optional[FlightRecorder]:
    return _active_recorder


# =========================================================================
# Recompile detection (jit cache misses)
# =========================================================================

_compile_lock = threading.Lock()
_compile_count = 0
_compile_seconds = 0.0
_compile_listener_installed = False


def _on_jax_event(event: str, duration_secs: float, **_kw) -> None:
    global _compile_count, _compile_seconds
    if not event.startswith("/jax/core/compile"):
        return
    with _compile_lock:
        # one backend_compile per executable build; trace/lower sub-phases
        # only contribute wall-time
        if event.endswith("backend_compile_duration"):
            _compile_count += 1
        _compile_seconds += duration_secs


def install_compile_listener() -> None:
    """Register the process-wide ``jax.monitoring`` listener (idempotent —
    jax offers no unregister, so exactly one is ever installed)."""
    global _compile_listener_installed
    with _compile_lock:
        if _compile_listener_installed:
            return
        _compile_listener_installed = True
    import jax.monitoring

    jax.monitoring.register_event_duration_secs_listener(_on_jax_event)


def compile_stats() -> Tuple[int, float]:
    """(total executable compiles, total compile wall-seconds) so far."""
    with _compile_lock:
        return _compile_count, _compile_seconds


def tree_shapes(tree: Any) -> Dict[str, str]:
    """Flat ``leaf-path -> shape/dtype`` map for arg-shape diffing."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = "/".join(str(getattr(k, "key", getattr(k, "idx", getattr(k, "name", k))))
                       for k in path)
        shape = getattr(leaf, "shape", ())
        dtype = getattr(leaf, "dtype", type(leaf).__name__)
        out[key] = f"{tuple(shape)}:{dtype}"
    return out


def shape_diff(old: Optional[Dict[str, str]],
               new: Dict[str, str]) -> Dict[str, Any]:
    """What changed between two shape maps — the offending diff logged with a
    recompile event."""
    if old is None:
        return {"initial": True}
    changed = {k: {"was": old[k], "now": v}
               for k, v in new.items() if k in old and old[k] != v}
    added = sorted(set(new) - set(old))
    removed = sorted(set(old) - set(new))
    out: Dict[str, Any] = {}
    if changed:
        out["changed"] = changed
    if added:
        out["added"] = added
    if removed:
        out["removed"] = removed
    return out or {"identical_shapes": True}


# =========================================================================
# Goodput accounting
# =========================================================================


class GoodputAccounter:
    """Attribute wall-clock since construction to named categories.

    ``other`` is the residual (total − sum of known categories), so the
    split accounts for 100% of measured wall-clock by construction — the
    report tool asserts ≥99% survives serialization/rounding.
    ``offload_stall`` is the exposed (non-overlapped) transfer wait inside
    offloaded steps — carved OUT of productive, because a step blocked on
    D2H/NVMe is exactly the time the offload pipeline exists to hide.
    ``rollback`` is the sentinel's recovery wall (last-good reload + data
    fast-forward, ``runtime/sentinel.py``) — carved out for the same
    reason: it is time training exists to avoid, and burying it in
    productive would hide exactly the cost a divergence inflicts."""

    CATEGORIES = ("productive", "checkpoint", "compile", "offload_stall",
                  "rollback", "startup", "other")

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._buckets: Dict[str, float] = {c: 0.0 for c in self.CATEGORIES
                                           if c != "other"}
        self._first_step_seen = False

    def account(self, category: str, seconds: float) -> None:
        if seconds < 0:
            return
        with self._lock:
            self._buckets[category] = self._buckets.get(category, 0.0) + seconds

    def mark_first_step(self) -> None:
        """Everything before the first step is startup (process boot, tracing
        done outside steps, checkpoint resume)."""
        with self._lock:
            if self._first_step_seen:
                return
            self._first_step_seen = True
            known = sum(self._buckets.values())
            self._buckets["startup"] = max(
                0.0, (self._clock() - self._t0) - known)

    def summary(self) -> Dict[str, float]:
        with self._lock:
            total = max(1e-9, self._clock() - self._t0)
            buckets = dict(self._buckets)
        known = sum(buckets.values())
        buckets["other"] = max(0.0, total - known)
        buckets["total"] = total
        buckets["productive_frac"] = buckets.get("productive", 0.0) / total
        return buckets

    def events(self, step: int) -> List[Event]:
        s = self.summary()
        ev: List[Event] = [(f"Goodput/{c}_s", s.get(c, 0.0), step)
                           for c in self.CATEGORIES]
        ev.append(("Goodput/total_s", s["total"], step))
        ev.append(("Goodput/productive_frac", s["productive_frac"], step))
        return ev


# =========================================================================
# Heartbeat
# =========================================================================


class Heartbeat:
    """Per-rank freshness file: ``{"t", "step", "pid"}``, rewritten atomically
    at most every ``interval_s``. The elastic agent compares the recorded
    wall time against its clock to tell a hung worker from a slow one."""

    def __init__(self, path: str, interval_s: float = 1.0,
                 clock: Callable[[], float] = time.time):
        self.path = path
        self.interval_s = float(interval_s)
        self._clock = clock
        self._last: Optional[float] = None  # first beat always writes
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def beat(self, step: int, force: bool = False) -> bool:
        now = self._clock()
        if not force and self._last is not None \
                and now - self._last < self.interval_s:
            return False
        self._last = now
        tmp = f"{self.path}.tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump({"t": now, "step": int(step), "pid": os.getpid()}, f)
            os.replace(tmp, self.path)
        except OSError as e:  # heartbeat failure must never kill training
            logger.warning("heartbeat write failed: %s", e)
            return False
        return True

    @staticmethod
    def read(path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    @staticmethod
    def age(path: str, now: Optional[float] = None) -> Optional[float]:
        """Seconds since the last beat, or None if unreadable."""
        hb = Heartbeat.read(path)
        if hb is None or "t" not in hb:
            return None
        # cross-PROCESS freshness: the beat's "t" is another process's wall
        # clock, so the comparison clock must be wall too (same host)
        return (now if now is not None
                else time.time()) - float(hb["t"])  # dslint: allow(wall-clock-in-step-path)


# =========================================================================
# Prometheus textfile rendering (export_textfile)
# =========================================================================

_PROM_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def prometheus_name(name: str) -> str:
    """Metric-registry name → Prometheus metric name (``Serve/ttft_s`` →
    ``dstpu_Serve_ttft_s``)."""
    out = _PROM_BAD_CHARS.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return f"dstpu_{out}"


def render_prometheus(snapshot: Dict[str, Any],
                      labels: Optional[Dict[str, str]] = None) -> str:
    """Render a :meth:`MetricsRegistry.snapshot` (plus any extra scalar maps
    merged into its ``counters``/``gauges``) as Prometheus text exposition
    format — the textfile-collector contract: a node exporter (or any
    scraper) reads the file, so long multi-host runs are observable without
    ever parsing JSONL."""
    label_str = ""
    if labels:
        inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        label_str = "{" + inner + "}"
    lines: List[str] = []
    for name, value in sorted(snapshot.get("counters", {}).items()):
        pname = prometheus_name(name)
        lines.append(f"# TYPE {pname} counter")
        lines.append(f"{pname}{label_str} {value}")
    for name, value in sorted(snapshot.get("gauges", {}).items()):
        pname = prometheus_name(name)
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname}{label_str} {value}")
    for name, h in sorted(snapshot.get("histograms", {}).items()):
        pname = prometheus_name(name)
        lines.append(f"# TYPE {pname} histogram")
        cum = 0
        for edge, count in zip(h["buckets"], h["counts"]):
            cum += count
            le = ("{" + (label_str[1:-1] + "," if label_str else "")
                  + f'le="{edge}"' + "}")
            lines.append(f"{pname}_bucket{le} {cum}")
        cum += h["counts"][-1]
        le_inf = ("{" + (label_str[1:-1] + "," if label_str else "")
                  + 'le="+Inf"' + "}")
        lines.append(f"{pname}_bucket{le_inf} {cum}")
        lines.append(f"{pname}_sum{label_str} {h['sum']}")
        lines.append(f"{pname}_count{label_str} {h['count']}")
    return "\n".join(lines) + "\n"


def export_metrics_textfile(path: str, snapshot: Dict[str, Any],
                            labels: Optional[Dict[str, str]] = None,
                            extra_counters: Optional[Dict[str, Any]] = None
                            ) -> str:
    """Write one registry snapshot as a Prometheus textfile-collector file
    with the atomic-rename contract (write ``<path>.tmp<pid>``, then
    ``os.replace`` — a scraper never observes a torn file). The single
    implementation behind :meth:`Telemetry.export_textfile` (training,
    rank-labelled) and the serving plane (``serve_worker`` per-replica
    journals dir, ``FleetRouter`` beside its stream) so both sides share
    one cumulative-bucket/labeling contract. Failure is a warning, never
    fatal — export must not kill the workload."""
    if extra_counters:
        snapshot = {**snapshot,
                    "counters": {**snapshot.get("counters", {}),
                                 **extra_counters}}
    text = render_prometheus(snapshot, labels=labels)
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except OSError as e:  # export failure must never kill the workload
        logger.warning("textfile export failed: %s", e)
    return path


_anchor_lock = threading.Lock()
_anchor_counter = 0


def _next_anchor_seq() -> int:
    """Process-global anchor epoch counter: two anchored engines in one
    process must stamp DISTINCT sync epochs or their step spans would
    collide on the pod aggregator's (sync, step) fusion keys. Ranks stay in
    lockstep because :meth:`Telemetry.anchor` is a collective — every rank
    performs the same anchor calls in the same order."""
    global _anchor_counter
    with _anchor_lock:
        _anchor_counter += 1
        return _anchor_counter


_faulthandler_installed = False


def install_hang_dump(stack_path: str) -> bool:
    """Register ``faulthandler`` on SIGUSR1 so the elastic agent can demand a
    stack dump from a hung worker before restarting it. Idempotent; returns
    whether the handler is (now) installed."""
    global _faulthandler_installed
    if _faulthandler_installed:
        return True
    import faulthandler
    import signal

    if not hasattr(signal, "SIGUSR1"):  # pragma: no cover - non-posix
        return False
    try:
        os.makedirs(os.path.dirname(stack_path) or ".", exist_ok=True)
        f = open(stack_path, "a")
        faulthandler.register(signal.SIGUSR1, file=f, all_threads=True)
    except (OSError, ValueError, RuntimeError) as e:  # pragma: no cover
        logger.warning("faulthandler hang-dump unavailable: %s", e)
        return False
    _faulthandler_installed = True
    return True


# =========================================================================
# Telemetry facade (what the engine holds)
# =========================================================================


class Telemetry:
    """Everything observability, wired together for one engine.

    The engine calls :meth:`on_step_end` after every ``train_batch``,
    :meth:`ckpt_span` around checkpoint saves, and the preemption handler
    calls :meth:`dump` before the process dies. Construction cost is one
    ring + (optionally) a JSONL file open; the per-step cost is a few dict
    appends — the <5% overhead guarantee lives in the tier-1 suite."""

    def __init__(self, cfg: Any, jsonl: Any = None, rank: int = 0):
        self.cfg = cfg
        self.rank = rank
        self.recorder = FlightRecorder(capacity=cfg.ring_size)
        self.registry = metrics_registry
        self.goodput = GoodputAccounter() if cfg.goodput_enabled else None
        self.jsonl = jsonl
        self._closed = False
        self._last_shapes: Optional[Dict[str, str]] = None
        self._compile_base = (0, 0.0)
        self._last_memory_step = -1
        self._last_step_end: Optional[float] = None
        self._step_hist = self.registry.histogram("step_time_s")
        # run-cumulative offload pipeline ledger (record_offload); the
        # Offload/* periodic events derive effective bandwidths from it
        self._offload_totals: Dict[str, float] = {}
        # run-cumulative health-sentinel ledger (record_health); the
        # Health/* periodic events are derived from it
        self._health_totals: Dict[str, Any] = {}
        # latest anchor epoch THIS telemetry stamped on its step spans; the
        # counter behind it is process-global (_next_anchor_seq) so two
        # anchored engines in one process get distinct epochs
        self._anchor_seq = 0
        self._last_textfile: Optional[float] = None
        # the engine parks its CollectiveWatchdog (comm/watchdog.py) here
        # so close() stops the poll thread — engines have no teardown of
        # their own, and a leaked 4 Hz daemon per engine adds up in
        # multi-engine processes
        self.watchdog: Any = None
        self.heartbeat: Optional[Heartbeat] = None
        if cfg.heartbeat_enabled:
            self.heartbeat = Heartbeat(
                os.path.join(cfg.output_dir, f"heartbeat_rank{rank}.json"),
                interval_s=cfg.heartbeat_interval_s)
            if cfg.stack_dump_on_hang:
                install_hang_dump(
                    os.path.join(cfg.output_dir, f"stacks_rank{rank}.txt"))
        install_compile_listener()
        self._compile_base = compile_stats()
        if jsonl is not None and hasattr(jsonl, "attach_recorder"):
            jsonl.attach_recorder(self.recorder)
        self.recorder.record(
            "meta", "flight_recorder/start",
            data={"rank": rank, "pid": os.getpid(), "version": 1,
                  "ring_size": cfg.ring_size})
        set_active_recorder(self.recorder)
        import atexit

        atexit.register(self.close)

    # ------------------------------------------------------------- step path
    def on_step_end(self, step: int, dur: Optional[float] = None,
                    batch: Any = None,
                    offload: Optional[Dict[str, Any]] = None) -> None:
        """Per-step accounting: step span into the ring, duration histogram,
        recompile attribution (with arg-shape diff), goodput, heartbeat and
        periodic memory gauges.

        ``dur`` is the caller-measured step wall; ``None`` (the eager
        ``forward/backward/step`` path) falls back to boundary-to-boundary
        timing — the whole gap since the previous step end, data time
        included. Either way this is HOST wall-clock: under async dispatch
        a span covers dispatch (throttled to device pace by XLA's bounded
        in-flight queue), and sync points land in goodput's ``other``. Set
        ``telemetry.sync_timing`` for device-accurate per-step spans at the
        cost of dispatch/compute overlap."""
        now = time.perf_counter()
        if dur is None:
            dur = (now - self._last_step_end
                   if self._last_step_end is not None else 0.0)
        self._last_step_end = now
        count, seconds = compile_stats()
        d_count = count - self._compile_base[0]
        d_seconds = seconds - self._compile_base[1]
        # rebase unconditionally: trace/lower durations arrive even without a
        # backend compile (cache hits, HLO re-lowering) and must not be
        # re-deducted from 'productive' on every later step
        self._compile_base = (count, seconds)
        span_data: Optional[Dict[str, Any]] = None
        if d_count > 0:
            self.registry.counter("recompiles").incr(d_count)
            new_shapes = tree_shapes(batch) if batch is not None else {}
            diff = shape_diff(self._last_shapes, new_shapes)
            self._last_shapes = new_shapes
            self.recorder.record("event", "compile/train_step", step=step,
                                 dur=d_seconds,
                                 data={"compiles": d_count,
                                       "shape_diff": diff})
            span_data = {"compiles": d_count, "compile_s": d_seconds}
        elif batch is not None and self._last_shapes is None:
            self._last_shapes = tree_shapes(batch)
        if self._anchor_seq:
            # barrier-anchored alignment epoch: lets the pod aggregator
            # (monitor/pod.py) fuse step N of THIS run across ranks without
            # confusing it with step N of a previous incarnation in the same
            # appended JSONL
            span_data = {**(span_data or {}), "sync": self._anchor_seq}
        self.recorder.record("span", "step", step=step, dur=dur,
                             data=span_data)
        self._step_hist.observe(dur)
        stall = 0.0
        if offload:
            self.record_offload(step, offload)
            stall = float(offload.get("stall_s", 0.0))
        if self.goodput is not None:
            # account this step BEFORE marking first-step: startup is the
            # residual of everything before it, so the first step's own
            # compile/compute must already be in their buckets or it would
            # be double-counted into startup
            compile_s = min(d_seconds, dur)
            self.goodput.account("compile", compile_s)
            # exposed offload stall is carved OUT of productive (clamped so
            # timing noise can't push productive negative — accounting
            # still sums to 100% by construction)
            stall_s = min(stall, max(0.0, dur - compile_s))
            if stall_s > 0:
                self.goodput.account("offload_stall", stall_s)
            self.goodput.account("productive",
                                 max(0.0, dur - compile_s - stall_s))
            self.goodput.mark_first_step()
        if self.heartbeat is not None:
            self.heartbeat.beat(step)
        if self.cfg.textfile_enabled:
            # heartbeat-cadence Prometheus snapshot: long multi-host runs
            # are scraped off this file without anyone tailing JSONL
            tnow = time.perf_counter()
            if self._last_textfile is None or \
                    tnow - self._last_textfile >= self.cfg.textfile_interval_s:
                self._last_textfile = tnow
                self.export_textfile()
        interval = self.cfg.memory_interval_steps
        if interval > 0 and step - self._last_memory_step >= interval:
            self._last_memory_step = step
            self.sample_memory(step)

    def sample_memory(self, step: int) -> Dict[str, int]:
        from ..accelerator import get_accelerator

        try:
            stats = get_accelerator().memory_stats() or {}
        except Exception as e:  # pragma: no cover - backend dependent
            logger.warning("memory_stats unavailable: %s", e)
            return {}
        in_use = int(stats.get("bytes_in_use", 0))
        peak = int(stats.get("peak_bytes_in_use", 0))
        self.registry.gauge("hbm_bytes_in_use").set(in_use)
        self.registry.gauge("hbm_peak_bytes_in_use").set(peak)
        self.recorder.record("gauge", "memory/hbm", step=step,
                             data={"bytes_in_use": in_use,
                                   "peak_bytes_in_use": peak})
        return {"bytes_in_use": in_use, "peak_bytes_in_use": peak}

    @contextlib.contextmanager
    def ckpt_span(self, what: str = "save", step: int = 0):
        """Wraps checkpoint saves: a ``ckpt`` span in the ring + goodput's
        checkpoint bucket. Forces heartbeats at entry/exit so a long save
        doesn't read as a silent gap — but a save longer than the agent's
        ``heartbeat_timeout`` will still be declared hung: size the timeout
        to cover the worst-case checkpoint, not just a step."""
        if self.heartbeat is not None:
            self.heartbeat.beat(step, force=True)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            self.recorder.record("span", f"ckpt/{what}", dur=dur)
            self.registry.histogram("ckpt_save_s").observe(dur)
            if self.goodput is not None:
                self.goodput.account("checkpoint", dur)
            if self.heartbeat is not None:
                self.heartbeat.beat(step, force=True)

    # ----------------------------------------------------- pod-scope hooks
    def anchor(self, tag: str = "start") -> int:
        """Record a barrier-anchored alignment point for cross-rank trace
        fusion (``monitor/pod.py``).

        Under multiple controllers every rank calls this together (the
        engine does, at construction — a collective contract like any
        barrier); all ranks exit the barrier at the same true instant, so
        the wall timestamp each rank records immediately after is the same
        physical moment seen through that rank's clock. The pod aggregator
        subtracts anchor timestamps to recover per-rank clock offsets —
        including any *constant* straggling that step-boundary alignment
        alone would silently absorb. Subsequent step spans carry the anchor
        sequence id (``data.sync``) so steps fuse within one anchored epoch
        only."""
        import jax

        seq = _next_anchor_seq()
        synced = True
        if jax.process_count() > 1:
            try:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices(f"dstpu_pod_anchor_{seq}")
            except Exception as e:  # pragma: no cover - backend dependent
                # the epoch marker is still valid (step spans need it to
                # separate epochs) but its timestamp is NOT a shared
                # instant — flag it so the pod aggregator falls back to
                # step-boundary alignment instead of trusting a fake offset
                logger.warning("pod anchor barrier unavailable (%s); "
                               "recording unsynchronized anchor", e)
                synced = False
        self._anchor_seq = seq
        self.recorder.record("meta", "align/anchor",
                             data={"anchor": seq, "tag": tag,
                                   "synced": synced})
        return seq

    def record_offload(self, step: int, stats: Dict[str, Any]) -> None:
        """Persist one offloaded step's transfer/compute ledger
        (``runtime/offload_pipeline.py`` ``OffloadStats.as_dict()`` shape)
        as an ``offload/step`` record, feed the byte counters, and
        accumulate the run totals behind the ``Offload/*`` periodic
        events. ``tools/trace_report.py`` renders the records offline."""
        self.recorder.record("event", "offload/step", step=step,
                             data=dict(stats))
        for key in ("d2h_bytes", "h2d_bytes", "nvme_read_bytes",
                    "nvme_write_bytes"):
            n = int(stats.get(key, 0) or 0)
            if n:
                self.registry.counter(f"offload_{key}").incr(n)
        t = self._offload_totals
        for key in ("d2h_bytes", "h2d_bytes", "nvme_read_bytes",
                    "nvme_write_bytes", "d2h_s", "h2d_s", "nvme_read_s",
                    "host_compute_s", "stall_s", "transfer_s"):
            t[key] = t.get(key, 0.0) + float(stats.get(key, 0.0) or 0.0)

    def offload_events(self, step: int) -> List[Event]:
        """``Offload/*`` scalar events from the cumulative ledger: bytes
        and effective GB/s per direction (bytes over transfer occupancy —
        conservative, since occupancy spans include overlapped compute),
        host-compute and exposed-stall seconds, and overlap efficiency."""
        t = self._offload_totals
        if not t:
            return []
        ev: List[Event] = []
        for direction in ("d2h", "h2d", "nvme_read"):
            nbytes = t.get(f"{direction}_bytes", 0.0)
            secs = t.get(f"{direction}_s", 0.0)
            ev.append((f"Offload/{direction}_bytes", nbytes, step))
            if secs > 0:
                ev.append((f"Offload/{direction}_gbps",
                           nbytes / 1e9 / secs, step))
        ev.append(("Offload/nvme_write_bytes",
                   t.get("nvme_write_bytes", 0.0), step))
        ev.append(("Offload/host_compute_s",
                   t.get("host_compute_s", 0.0), step))
        ev.append(("Offload/stall_s", t.get("stall_s", 0.0), step))
        if t.get("transfer_s", 0.0) > 0:
            # canonical definition lives in runtime/offload_pipeline.py
            # (imported lazily — monitor must stay import-light)
            from ..runtime.offload_pipeline import overlap_efficiency

            ev.append(("Offload/overlap_efficiency",
                       overlap_efficiency(t.get("stall_s", 0.0),
                                          t["transfer_s"]), step))
        return ev

    def record_health(self, step: int, data: Dict[str, Any]) -> None:
        """Persist one sentinel observation/decision (``runtime/sentinel.py``
        verdict shape: cause, z-scores, nonfinite count, action taken,
        per-region grad norms) as a ``health/step`` record and fold it into
        the run-cumulative ledger behind the ``Health/*`` periodic events.
        ``tools/trace_report.py`` renders the records offline."""
        self.recorder.record("event", "health/step", step=step,
                             data=dict(data))
        t = self._health_totals
        action = data.get("action")
        if action in ("warn", "skip", "rollback", "abort"):
            key = action + "s"
            t[key] = int(t.get(key, 0)) + 1
        t["nonfinite_count"] = (int(t.get("nonfinite_count", 0))
                                + int(data.get("nonfinite", 0) or 0))
        for key in ("loss_z", "grad_norm_z", "streak"):
            if data.get(key) is not None:
                t[f"last_{key}"] = float(data[key])
        for region, norm in (data.get("region_norms") or {}).items():
            t.setdefault("region_norms", {})[region] = float(norm)

    def health_events(self, step: int) -> List[Event]:
        """``Health/*`` scalar events from the cumulative sentinel ledger:
        ladder action counts, last observed robust z-scores, cumulative
        nonfinite gradient elements and the per-region grad-norm breakdown
        (named to the MFU region registry)."""
        t = self._health_totals
        if not t:
            return []
        ev: List[Event] = []
        for action in ("warns", "skips", "rollbacks", "aborts"):
            ev.append((f"Health/{action}", int(t.get(action, 0)), step))
        ev.append(("Health/nonfinite_count",
                   int(t.get("nonfinite_count", 0)), step))
        for key, name in (("last_loss_z", "Health/loss_z"),
                          ("last_grad_norm_z", "Health/grad_norm_z"),
                          ("last_streak", "Health/anomaly_streak")):
            if key in t:
                ev.append((name, t[key], step))
        for region, norm in sorted((t.get("region_norms") or {}).items()):
            ev.append((f"Health/grad_norm.{region}",  # dslint: allow(undeclared-event-name) registry-enumerated member builder
                       norm, step))
        return ev

    def record_census(self, census: Dict[str, Any]) -> None:
        """Persist a static collective-census class summary
        (``analysis/collectives.py`` ``CollectiveClasses.summary()`` shape,
        plus any context keys) into the stream — the pod report joins it
        against measured step spans for the per-traffic-class bytes/time/
        bandwidth decomposition."""
        self.recorder.record("event", "comm/census", data=census)

    def export_textfile(self, path: Optional[str] = None) -> str:
        """Write the current metrics-registry + resilience-counter state as
        a Prometheus textfile-collector snapshot (atomic rename, scrape-safe)
        and return the path. Called automatically at heartbeat cadence when
        ``telemetry.textfile.enabled`` is set; safe to call manually."""
        path = path or os.path.join(self.cfg.output_dir,
                                    f"metrics_rank{self.rank}.prom")
        return export_metrics_textfile(
            path, self.registry.snapshot(),
            labels={"rank": str(self.rank)},
            extra_counters={f"resilience_{k}": v for k, v in
                            resilience_counters.snapshot().items()})

    # ------------------------------------------------------------ reporting
    def periodic_events(self, step: int) -> List[Event]:
        """Scalar events for MonitorMaster at print boundaries: Goodput/*,
        Memory/*, Compile/*."""
        ev: List[Event] = []
        if self.goodput is not None:
            ev.extend(self.goodput.events(step))
        snap = self.registry.snapshot()
        g = snap["gauges"]
        if "hbm_bytes_in_use" in g:
            ev.append(("Memory/bytes_in_use", g["hbm_bytes_in_use"], step))
            ev.append(("Memory/peak_bytes_in_use",
                       g["hbm_peak_bytes_in_use"], step))
        count, seconds = compile_stats()
        ev.append(("Compile/count", count, step))
        ev.append(("Compile/total_s", seconds, step))
        if snap["counters"].get("ckpt_bytes_written"):
            ev.append(("Ckpt/bytes_written",
                       snap["counters"]["ckpt_bytes_written"], step))
        ckpt_hist = snap["histograms"].get("ckpt_save_s")
        if ckpt_hist and ckpt_hist["count"]:
            ev.append(("Ckpt/save_s", ckpt_hist["sum"], step))
        commit_hist = snap["histograms"].get("ckpt_pod_commit_s")
        if commit_hist and commit_hist["count"]:
            ev.append(("Ckpt/pod_commit_s", commit_hist["sum"], step))
        ev.extend(self.offload_events(step))
        ev.extend(self.health_events(step))
        return ev

    def dump(self, reason: str = "manual") -> List[Dict[str, Any]]:
        """Force the ring (and a goodput summary) onto disk — called by the
        preemption handler before the process exits."""
        if self.goodput is not None:
            self.recorder.record("goodput", "goodput/summary",
                                 data=self.goodput.summary())
        try:
            from ..comm.comms_logging import comms_logger

            if comms_logger.enabled:
                self.recorder.record("event", "comm/snapshot",
                                     data=comms_logger.snapshot())
        except Exception:  # pragma: no cover - defensive
            pass
        records = self.recorder.dump(reason)
        if self.jsonl is not None:
            try:
                self.jsonl.flush()
            except Exception as e:
                logger.warning("telemetry dump: jsonl flush failed: %s", e)
        if self.cfg.textfile_enabled:
            # the scrape file must reflect the final state too — a scraper
            # polling a preempted run otherwise reads a stale snapshot
            self.export_textfile()
        return records

    def close(self, reason: str = "shutdown") -> None:
        """Idempotent shutdown: final goodput summary + dump + sink flush."""
        if self._closed:
            return
        self._closed = True
        import atexit

        try:  # py>=3.9: drop our strong atexit ref so closed telemetries
            atexit.unregister(self.close)  # don't pin their rings for life
        except Exception:  # pragma: no cover - defensive
            pass
        try:
            self.dump(reason)
        finally:
            if self.watchdog is not None:
                try:
                    self.watchdog.stop()
                except Exception:  # pragma: no cover - defensive
                    pass
            if get_active_recorder() is self.recorder:
                set_active_recorder(None)


def build_telemetry(config: Any, monitor: Any) -> Optional[Telemetry]:
    """Engine-side factory: returns a wired :class:`Telemetry` or ``None``
    when the ``telemetry`` config section is off (and ``DSTPU_TELEMETRY``
    doesn't force it). Ensures a rank-local ``JsonlMonitor`` backend exists
    on the given :class:`~.monitor.MonitorMaster` and attaches the flight
    recorder to it."""
    tcfg = config.telemetry
    forced = os.environ.get("DSTPU_TELEMETRY", "").lower() in ("1", "true")
    if not (tcfg.enabled or forced):
        return None
    from .monitor import JsonlMonitor
    from ..utils.podid import pod_rank

    # pod identity, not jax.process_index: an env-declared pod of
    # independent single-controller replicas (utils/podid.py) must still
    # write DISTINCT flightrec_rank<N>.jsonl / heartbeat files, or the pod
    # report and the agent's heartbeat glob see one rank where N exist
    rank = pod_rank()
    jsonl = next((m for m in monitor.monitors
                  if isinstance(m, JsonlMonitor)), None)
    if jsonl is None:
        jsonl = JsonlMonitor(
            path=os.path.join(tcfg.output_dir,
                              f"flightrec_rank{rank}.jsonl"),
            flush_interval=tcfg.flush_interval_records)
        monitor.monitors.append(jsonl)
        monitor.enabled = True
    return Telemetry(tcfg, jsonl=jsonl, rank=rank)
