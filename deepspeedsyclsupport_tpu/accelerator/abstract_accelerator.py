"""Abstract accelerator interface.

TPU-native analog of the reference's pluggable-backend seam
(``accelerator/abstract_accelerator.py:10-277`` — ``DeepSpeedAccelerator`` ABC with ~60
abstract methods for device mgmt, RNG, streams, memory stats, dtype support, op builders).

In a JAX design most of those methods collapse: there are no user-visible streams or
pinned-memory pools (XLA owns scheduling and transfers), and kernels are Pallas functions
rather than JIT-compiled C++ extensions. What survives is the *seam itself*: every device
touch in the runtime goes through :func:`get_accelerator`, so swapping TPU ⇄ CPU-sim ⇄ GPU
is one registry change, exactly like the reference swaps cuda/xpu/cpu backends.
"""
import abc
from typing import Any, Dict, List, Optional, Sequence


class Accelerator(abc.ABC):
    """Device backend interface: naming, devices, dtypes, memory, RNG, collectives name.

    Mirrors the surface of the reference ABC that is meaningful under XLA. Methods that
    exist purely because of CUDA semantics (streams, events, graph capture, pinned
    allocators) are intentionally absent: XLA's async dispatch plays the role of streams,
    and compiled executables play the role of CUDA graphs.
    """

    # ------------------------------------------------------------------ identity
    @abc.abstractmethod
    def name(self) -> str:
        """Backend name: 'tpu' or 'cpu' (simulated mesh)."""

    @abc.abstractmethod
    def communication_backend_name(self) -> str:
        """Name of the collective transport (reference: nccl/ccl/hccl).

        On TPU this is the ICI/DCN fabric driven by XLA collectives; on the CPU
        simulator it is the host 'gloo-like' XLA CPU collectives.
        """

    # ------------------------------------------------------------------ devices
    @abc.abstractmethod
    def devices(self) -> List[Any]:
        """All addressable jax devices for this backend."""

    def device_count(self) -> int:
        return len(self.devices())

    @abc.abstractmethod
    def is_available(self) -> bool:
        """True if this backend has at least one live device."""

    def current_device(self) -> Any:
        return self.devices()[0]

    def synchronize(self, tree: Any = None) -> None:
        """Block until async dispatch has drained (reference: device synchronize)."""
        import jax

        if tree is None:
            # effects_barrier waits for all in-flight computations.
            jax.effects_barrier()
        else:
            jax.block_until_ready(tree)

    # ------------------------------------------------------------------ dtypes
    def supported_dtypes(self) -> List[Any]:
        import jax.numpy as jnp

        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8, jnp.int32]

    def preferred_dtype(self) -> Any:
        """Default low-precision compute dtype (bf16 is TPU-native)."""
        import jax.numpy as jnp

        return jnp.bfloat16

    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True

    # ------------------------------------------------------------------ memory
    def memory_stats(self, device: Optional[Any] = None) -> Dict[str, int]:
        """Per-device memory statistics (reference: memory_allocated/max_memory etc.)."""
        dev = device or self.current_device()
        stats = getattr(dev, "memory_stats", lambda: None)()
        return dict(stats) if stats else {}

    def available_memory(self, device: Optional[Any] = None) -> Optional[int]:
        stats = self.memory_stats(device)
        if "bytes_limit" in stats:
            return stats["bytes_limit"] - stats.get("bytes_in_use", 0)
        return None

    def total_memory(self, device: Optional[Any] = None) -> Optional[int]:
        stats = self.memory_stats(device)
        return stats.get("bytes_limit")

    # ------------------------------------------------------------------ RNG
    def default_rng(self, seed: int):
        import jax

        return jax.random.PRNGKey(seed)

    # ------------------------------------------------------------------ introspection
    def device_kind(self) -> str:
        try:
            return self.devices()[0].device_kind
        except Exception:
            return "unknown"

    def platform(self) -> str:
        try:
            return self.devices()[0].platform
        except Exception:
            return self.name()

    def on_tpu(self) -> bool:
        return self.platform() in ("tpu", "axon")


def literal_device_count(backend: Optional[str] = None) -> int:
    import jax

    return jax.device_count(backend) if backend else jax.device_count()
