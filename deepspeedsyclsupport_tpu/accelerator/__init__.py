from .abstract_accelerator import Accelerator
from .real_accelerator import (
    CpuAccelerator,
    GpuAccelerator,
    TpuAccelerator,
    get_accelerator,
    reset_accelerator,
    set_accelerator,
)

__all__ = [
    "Accelerator",
    "CpuAccelerator",
    "GpuAccelerator",
    "TpuAccelerator",
    "get_accelerator",
    "set_accelerator",
    "reset_accelerator",
]
