"""Accelerator selection registry.

TPU-native analog of the reference's ``accelerator/real_accelerator.py:23,51-192``:
env-var override (theirs: ``DS_ACCELERATOR``; ours: ``DSTPU_ACCELERATOR``) plus
import-probing auto-detect (theirs probes ipex/torch_npu/mps; ours probes the live JAX
platform). One process-global accelerator object, settable for tests.
"""
import os
from typing import Optional

from .abstract_accelerator import Accelerator

SUPPORTED_ACCELERATOR_LIST = ["tpu", "cpu", "gpu"]

_ACCELERATOR: Optional[Accelerator] = None


class _JaxAccelerator(Accelerator):
    """Concrete accelerator bound to one JAX platform string."""

    def __init__(self, platform_name: str):
        self._platform = platform_name

    def name(self) -> str:
        return self._platform

    def communication_backend_name(self) -> str:
        return {"tpu": "ici", "gpu": "nccl"}.get(self._platform, "xla-cpu")

    def devices(self):
        import jax

        try:
            if self._platform == "tpu":
                # The tunnel may expose TPU under an experimental platform name;
                # fall back to the default backend's devices.
                for plat in ("tpu", "axon"):
                    try:
                        devs = jax.devices(plat)
                        if devs:
                            return devs
                    except RuntimeError:
                        continue
                return jax.devices()
            return jax.devices(self._platform)
        except RuntimeError:
            return []

    def is_available(self) -> bool:
        return len(self.devices()) > 0


class TpuAccelerator(_JaxAccelerator):
    def __init__(self):
        super().__init__("tpu")


class CpuAccelerator(_JaxAccelerator):
    def __init__(self):
        super().__init__("cpu")
        import jax

        # Site-level TPU plugins may force jax_platforms to a remote backend at
        # interpreter start; a CPU accelerator must never trigger that backend's
        # (possibly blocking) initialization when the topology asks for devices.
        # Pinning is only possible before any backend initialized.
        try:
            from jax._src import xla_bridge

            if not xla_bridge.backends_are_initialized():
                jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    def preferred_dtype(self):
        import jax.numpy as jnp

        # CPU simulation keeps bf16 to mirror TPU numerics in tests.
        return jnp.bfloat16


class GpuAccelerator(_JaxAccelerator):
    def __init__(self):
        super().__init__("gpu")


def _detect() -> Accelerator:
    """Auto-detect: honor DSTPU_ACCELERATOR, else probe live platforms (tpu > gpu > cpu)."""
    override = os.environ.get("DSTPU_ACCELERATOR")
    if override:
        if override not in SUPPORTED_ACCELERATOR_LIST:
            raise ValueError(
                f"DSTPU_ACCELERATOR={override!r} not in {SUPPORTED_ACCELERATOR_LIST}")
        return {"tpu": TpuAccelerator, "cpu": CpuAccelerator, "gpu": GpuAccelerator}[override]()

    import jax

    platform = jax.default_backend()
    if platform in ("tpu", "axon"):
        return TpuAccelerator()
    if platform in ("gpu", "cuda", "rocm"):
        return GpuAccelerator()
    return CpuAccelerator()


def get_accelerator() -> Accelerator:
    """Process-global accelerator (reference: ``real_accelerator.py:51``)."""
    global _ACCELERATOR
    if _ACCELERATOR is None:
        _ACCELERATOR = _detect()
    return _ACCELERATOR


def set_accelerator(acc: Accelerator) -> None:
    """Explicit override (reference: ``real_accelerator.py:195``)."""
    global _ACCELERATOR
    _ACCELERATOR = acc


def reset_accelerator() -> None:
    global _ACCELERATOR
    _ACCELERATOR = None
