"""Import-path compat: ``deepspeed.checkpointing`` (reference
``runtime/activation_checkpointing/checkpointing.py``).

Under XLA, activation checkpointing is ``jax.checkpoint``; the config
knobs (partition_activations, cpu_checkpointing, ...) map to checkpoint
POLICIES selected via the engine's ``activation_checkpointing`` section
(see runtime/config.py). This module keeps the reference's call surface
for ported model code.
"""
from typing import Any, Callable

import jax

from .utils.logging import logger

_CONFIGURED = False
_POLICY = None


def checkpoint(function: Callable, *args) -> Any:
    """Reference ``checkpointing.checkpoint(fn, *args)``: run ``fn`` under
    rematerialization. Returns fn's outputs; gradients recompute the
    forward instead of saving activations."""
    return jax.checkpoint(function, policy=_POLICY)(*args)


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """Reference ``checkpointing.configure``. Partitioning/contiguity are
    XLA's job under GSPMD; ``checkpoint_in_cpu`` selects the host-offload
    remat policy (the cpu_checkpointing analog)."""
    global _CONFIGURED, _POLICY
    _CONFIGURED = True
    if checkpoint_in_cpu:
        _POLICY = jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")
        logger.info("checkpointing.configure: dot activations offload to "
                    "pinned host memory")
    else:
        _POLICY = None  # reconfiguration must clear a stale offload policy
    return None


def is_configured() -> bool:
    """Reference ``checkpointing.is_configured``."""
    return _CONFIGURED


def reset():
    global _CONFIGURED, _POLICY
    _CONFIGURED = False
    _POLICY = None
