"""Benchmark harness — rungs run cheapest-first, one JSON line per success.

Rungs (each an isolated child process so a hang/OOM in one cannot eat the
others' window):
  probe    — which platform actually answers (the axon TPU tunnel can hang)
  kernels  — COMPILED (non-interpret) Pallas parity + throughput microbench:
             flash fwd / fwd+bwd, ragged paged prefill, paged decode, each
             against its jnp oracle (TPU only — interpret numbers are not
             kernel evidence)
  train    — the training-MFU ladder on the flagship Llama-family model
  serve    — FastGen-style serving benchmark on the v2 ragged engine:
             closed-loop clients, p50/p95 TTFT, decode tokens/sec/chip, and
             a SplitFuse-on/off A-B (reference headline: 2.3x effective
             throughput, ``blogs/deepspeed-fastgen/README.md:28,139``)

The FINAL line aggregates every rung result under ``detail.rungs`` so a
parser that keeps only the last JSON line still sees everything.
``vs_baseline`` semantics per rung are in each line's ``detail.baseline``.

Resilience contract (round-1/2 postmortems: BENCH_r01 rc=1 on backend init,
BENCH_r02 silently degraded to CPU): this script ALWAYS exits 0 and ALWAYS
prints at least one valid JSON line; TPU rungs that hang or die fall back to
CPU where that still yields a meaningful regression number (train/serve),
and the platform is recorded honestly in every line.
"""
import json
import os
import subprocess
import sys
import time

# bf16 peak FLOPs and HBM bandwidth by platform (per chip)
PEAKS = {"tpu": 197e12,   # TPU v5e
         "cpu": 1e12}     # nominal, for smoke runs off-TPU
HBM_GBPS = {"tpu": 819.0, "cpu": 50.0}
REFERENCE_MFU = 0.54       # Ulysses 175/312 TFLOPs on A100 (BASELINE.md)
REFERENCE_FASTGEN_SPEEDUP = 2.3  # FastGen effective-throughput headline
RUNG_ENV = "DSTPU_BENCH_RUNG"


def _emit(result):
    print(json.dumps(result), flush=True)


def _child_jax():
    """Import jax honouring a JAX_PLATFORMS override — the axon
    sitecustomize force-pins jax_platforms at interpreter start, so the env
    var alone cannot steer the child; re-pin via jax.config before any
    backend initializes."""
    import jax

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    return jax


def _sync(x):
    """Reliable device barrier: fetch a value. On the axon remote-TPU
    platform block_until_ready can return before the dispatch chain
    finishes; a value fetch is the true barrier."""
    import numpy as np

    return float(np.asarray(x).reshape(-1)[0])


# ======================================================================
# rung: probe
# ======================================================================
def run_probe():
    jax = _child_jax()
    dev = jax.devices()[0]
    _emit({"metric": "probe", "value": len(jax.devices()), "unit": "devices",
           "vs_baseline": 1.0, "detail": {"platform": dev.platform}})


# ======================================================================
# rung: kernels (compiled Pallas vs jnp oracle — TPU only)
# ======================================================================
def _rel_err(got, want):
    import numpy as np

    g, w = np.asarray(got, np.float32), np.asarray(want, np.float32)
    return float(np.max(np.abs(g - w)) / (np.max(np.abs(w)) + 1e-9))


def _bench_loop(fn, args, iters):
    out = fn(*args)
    _sync(out[0] if isinstance(out, tuple) else out)  # warm/compile
    out = fn(*args)
    _sync(out[0] if isinstance(out, tuple) else out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync(out[0] if isinstance(out, tuple) else out)
    return (time.perf_counter() - t0) / iters


def _dense_attn_ref(q, k, v, causal=True):
    import jax
    import jax.numpy as jnp
    import numpy as np

    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) / np.sqrt(d)
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        m = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
        s = jnp.where(m, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, vf).astype(q.dtype)


def _make_atoms(lens, bq, block_size, h, kvh, d, key, dtype):
    """Synthetic ragged prefill batch: one atom per bq-row chunk of each
    sequence, disjoint block tables, full-prefill positions."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    bps = max(-(-ln // block_size) for ln in lens)
    pos0, qlen, atom_tbl = [], [], []
    next_blk = 0
    for ln in lens:
        nb = -(-ln // block_size)
        row = list(range(next_blk, next_blk + nb)) + [0] * (bps - nb)
        next_blk += nb
        for a0 in range(0, ln, bq):
            pos0.append(a0)
            qlen.append(min(bq, ln - a0))
            atom_tbl.append(row)
    slots = next_blk * block_size
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (len(pos0), bq, h, d), dtype)
    k = jax.random.normal(ks[1], (slots, kvh, d), dtype)
    v = jax.random.normal(ks[2], (slots, kvh, d), dtype)
    return (q, k, v, jnp.asarray(np.asarray(atom_tbl, np.int32)),
            jnp.asarray(pos0, dtype=jnp.int32),
            jnp.asarray(qlen, dtype=jnp.int32))


def run_kernels_micro():
    """<60s compiled-kernel evidence: ONE Pallas kernel (flash fwd), f32
    parity at small shape + bf16 throughput at production shape. Runs FIRST
    on TPU so even a brief tunnel window banks a compiled-kernel line
    (VERDICT r3 #1: three rounds with zero real-TPU evidence)."""
    jax = _child_jax()
    import jax.numpy as jnp

    from deepspeedsyclsupport_tpu.ops import flash_attention as fa

    platform = jax.devices()[0].platform
    smoke = bool(os.environ.get("DSTPU_BENCH_SMOKE"))
    if platform != "tpu" and not smoke:
        print("kernels_micro requires TPU; skipping", file=sys.stderr)
        return
    peak = PEAKS[platform]
    key = jax.random.PRNGKey(0)
    t0 = time.perf_counter()

    ks = jax.random.split(key, 3)
    q32 = jax.random.normal(ks[0], (1, 256, 4, 64), jnp.float32)
    got = jax.jit(lambda *a: fa.flash_attention(*a, causal=True))(
        q32, q32, q32)
    want = jax.jit(_dense_attn_ref)(q32, q32, q32)
    err = _rel_err(got, want)

    b, s, h, d = (1, 256, 2, 64) if smoke else (4, 2048, 16, 128)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, s, h, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, s, h, d), jnp.bfloat16)
    fwd = jax.jit(lambda q, k, v: fa.flash_attention(q, k, v, causal=True))
    dt = _bench_loop(fwd, (q, k, v), 2 if smoke else 10)
    tflops = 4 * b * h * s * s * d * 0.5 / dt / 1e12
    _emit({"metric": "kernel_micro_flash_fwd", "value": round(tflops, 2),
           "unit": "TFLOP/s",
           "vs_baseline": round(tflops * 1e12 / peak / REFERENCE_MFU, 4),
           "detail": {"platform": platform, "shape": [b, s, h, d],
                      "dtype": "bfloat16", "parity_max_rel_err": err,
                      "parity_ok": err < 5e-2,
                      "wall_s": round(time.perf_counter() - t0, 1),
                      "baseline": "fraction of chip peak vs reference "
                                  "54% MFU"}})


def run_kernels():
    jax = _child_jax()
    import functools

    import jax.numpy as jnp
    import numpy as np

    from deepspeedsyclsupport_tpu.ops import flash_attention as fa
    from deepspeedsyclsupport_tpu.ops import paged_attention as pa

    platform = jax.devices()[0].platform
    smoke = bool(os.environ.get("DSTPU_BENCH_SMOKE"))
    if platform != "tpu" and not smoke:
        print("kernels rung requires TPU (interpret mode is not kernel "
              "evidence); skipping", file=sys.stderr)
        return
    interp = platform != "tpu"  # smoke mode only: validate the rung's flow
    peak, bw = PEAKS[platform], HBM_GBPS[platform]
    key = jax.random.PRNGKey(0)

    # -------- flash attention: parity (f32, with grads) ------------------
    ks = jax.random.split(key, 4)
    b, s, h, d = 2, 512, 4, 64
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, h, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, h, d), jnp.float32)
    got = jax.jit(lambda *a: fa.flash_attention(*a, causal=True))(q, k, v)
    want = jax.jit(_dense_attn_ref)(q, k, v)
    fwd_err = _rel_err(got, want)

    def loss(f):
        return lambda q, k, v: (f(q, k, v) * v).astype(jnp.float32).sum()

    g_got = jax.jit(jax.grad(loss(
        lambda *a: fa.flash_attention(*a, causal=True)), (0, 1, 2)))(q, k, v)
    g_want = jax.jit(jax.grad(loss(_dense_attn_ref), (0, 1, 2)))(q, k, v)
    bwd_err = max(_rel_err(a_, b_) for a_, b_ in zip(g_got, g_want))

    # -------- flash attention: throughput (bf16) -------------------------
    b, s, h, d = (1, 256, 2, 64) if smoke else (4, 2048, 16, 128)
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, s, h, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, s, h, d), jnp.bfloat16)
    fwd = jax.jit(lambda q, k, v: fa.flash_attention(q, k, v, causal=True))
    dt = _bench_loop(fwd, (q, k, v), 20)
    flops_fwd = 4 * b * h * s * s * d * 0.5  # 2 matmuls, causal half
    tflops = flops_fwd / dt / 1e12
    _emit({"metric": "kernel_flash_fwd", "value": round(tflops, 2),
           "unit": "TFLOP/s",
           "vs_baseline": round(tflops * 1e12 / peak / REFERENCE_MFU, 4),
           "detail": {"platform": platform, "shape": [b, s, h, d],
                      "dtype": "bfloat16", "parity_max_rel_err": fwd_err,
                      "parity_ok": fwd_err < 5e-2,
                      "baseline": "fraction of chip peak vs reference 54% MFU"}})

    bwd = jax.jit(jax.grad(
        lambda q, k, v: fa.flash_attention(q, k, v, causal=True)
        .astype(jnp.float32).sum(), (0, 1, 2)))
    dt = _bench_loop(bwd, (q, k, v), 10)
    flops_fb = flops_fwd * 3.5  # grad call = fwd (2 matmuls) + bwd (5)
    tflops = flops_fb / dt / 1e12
    _emit({"metric": "kernel_flash_bwd", "value": round(tflops, 2),
           "unit": "TFLOP/s",
           "vs_baseline": round(tflops * 1e12 / peak / REFERENCE_MFU, 4),
           "detail": {"platform": platform, "shape": [b, s, h, d],
                      "dtype": "bfloat16", "parity_max_rel_err": bwd_err,
                      "parity_ok": bwd_err < 5e-2,
                      "baseline": "fraction of chip peak vs reference 54% MFU"}})

    # -------- ragged paged prefill: parity (f32, GQA) --------------------
    at = _make_atoms([96, 64, 33], 32, 16, 4, 2, 32, jax.random.PRNGKey(1),
                     jnp.float32)
    kern = functools.partial(pa.ragged_prefill_attention_pallas,
                             block_size=16, interpret=interp)
    got = jax.jit(kern)(*at)
    want = jax.jit(functools.partial(pa.ragged_prefill_attention_reference,
                                     block_size=16))(*at)
    valid = np.asarray(jnp.arange(32)[None, :] < at[5][:, None])
    pre_err = _rel_err(np.asarray(got)[valid], np.asarray(want)[valid])

    # -------- ragged paged prefill: throughput (bf16) --------------------
    lens = ([128, 64] if smoke
            else [2048, 1536, 1024, 1024, 512, 512, 256, 256])
    at = _make_atoms(lens, 128, 64, 16, 16, 128, jax.random.PRNGKey(2),
                     jnp.bfloat16)
    kern = jax.jit(functools.partial(pa.ragged_prefill_attention_pallas,
                                     block_size=64, interpret=interp))
    dt = _bench_loop(kern, at, 2 if smoke else 10)
    flops = sum(2 * 16 * 128 * ln * ln for ln in lens)  # causal half of 4
    tflops = flops / dt / 1e12
    _emit({"metric": "kernel_ragged_prefill", "value": round(tflops, 2),
           "unit": "TFLOP/s",
           "vs_baseline": round(tflops * 1e12 / peak / REFERENCE_MFU, 4),
           "detail": {"platform": platform, "seq_lens": lens,
                      "dtype": "bfloat16", "parity_max_rel_err": pre_err,
                      "parity_ok": pre_err < 5e-2,
                      "baseline": "fraction of chip peak vs reference 54% MFU"}})

    # -------- paged decode: parity (f32) then bandwidth (bf16) -----------
    def decode_setup(slots, bps, block, h, kvh, d, dtype, seed):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        nb = slots * bps
        q = jax.random.normal(ks[0], (slots, h, d), dtype)
        kc = jax.random.normal(ks[1], (nb * block, kvh, d), dtype)
        vc = jax.random.normal(ks[2], (nb * block, kvh, d), dtype)
        tables = jnp.arange(nb, dtype=jnp.int32).reshape(slots, bps)
        lens_ = jnp.full((slots,), bps * block, jnp.int32)
        return q, kc, vc, tables, lens_

    args = decode_setup(4, 3, 16, 4, 2, 32, jnp.float32, 3)
    got = jax.jit(functools.partial(pa.paged_decode_attention_pallas,
                                    block_size=16, interpret=interp))(*args)
    want = jax.jit(functools.partial(pa.paged_decode_attention_reference,
                                     block_size=16))(*args)
    dec_err = _rel_err(got, want)

    slots, bps, block, h, d = ((4, 2, 16, 2, 64) if smoke
                               else (64, 16, 64, 16, 128))
    args = decode_setup(slots, bps, block, h, h, d, jnp.bfloat16, 4)
    kern = jax.jit(functools.partial(pa.paged_decode_attention_pallas,
                                     block_size=block, interpret=interp))
    dt = _bench_loop(kern, args, 2 if smoke else 20)
    bytes_moved = slots * bps * block * h * d * 2 * 2  # K+V, bf16
    gbps = bytes_moved / dt / 1e9
    _emit({"metric": "kernel_paged_decode", "value": round(gbps, 1),
           "unit": "GB/s",
           "vs_baseline": round(gbps / bw, 4),
           "detail": {"platform": platform,
                      "slots": slots, "context": bps * block,
                      "dtype": "bfloat16", "parity_max_rel_err": dec_err,
                      "parity_ok": dec_err < 5e-2,
                      "baseline": "fraction of HBM peak bandwidth "
                                  "(decode attention is BW-bound)"}})


# ======================================================================
# rung: train (MFU ladder)
# ======================================================================
def model_flops_per_token(cfg):
    """6·N_active for the matmuls + attention quadratic term."""
    n_active = cfg.param_count()
    if cfg.num_experts > 0:
        dense_mlp = 3 * cfg.hidden_size * cfg.intermediate_size * cfg.num_layers
        n_active -= dense_mlp * (cfg.num_experts - cfg.num_experts_per_tok)
    attn = 12 * cfg.num_layers * cfg.hidden_size  # ≈ per token at seq S: *S below
    return 6 * n_active, attn


def _measure(name, seq, micro_bs, steps, remat, platform):
    """One bench rung: build → warmup/compile → timed steps → metrics dict.
    Raises on OOM/compile failure; the caller's ladder steps down."""
    import jax
    import numpy as np

    import deepspeedsyclsupport_tpu as ds
    from deepspeedsyclsupport_tpu.comm.topology import reset_world_topology
    from deepspeedsyclsupport_tpu.models import build_model, get_config

    cfg = get_config(name, remat=remat, max_seq_len=seq)
    reset_world_topology()
    topo = ds.build_topology(dp=1)
    model = build_model(cfg)
    config = {
        "train_batch_size": micro_bs,
        "train_micro_batch_size_per_gpu": micro_bs,
        "optimizer": {"type": "adam", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, topology=topo)
    batch = {"input_ids": jax.random.randint(jax.random.PRNGKey(0),
                                             (micro_bs, seq), 0,
                                             cfg.vocab_size)}
    for _ in range(2):
        m = engine.train_batch(batch)
    _sync(m["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        m = engine.train_batch(batch)
    _sync(m["loss"])
    dt = time.perf_counter() - t0

    tokens = steps * micro_bs * seq
    tok_per_sec = tokens / dt
    f_matmul, f_attn = model_flops_per_token(cfg)
    flops_per_token = f_matmul + f_attn * seq
    achieved = tok_per_sec * flops_per_token
    mfu = achieved / PEAKS.get(platform, PEAKS["cpu"])
    return {
        "metric": f"train_tokens_per_sec_per_chip_{name}_seq{seq}",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / REFERENCE_MFU, 4),
        "detail": {"platform": platform, "mfu": round(mfu, 4),
                   "tflops": round(achieved / 1e12, 2),
                   "micro_bs": micro_bs, "remat": remat,
                   "baseline": "achieved MFU vs reference 54% (Ulysses "
                               "175/312 TFLOPs on A100)",
                   "loss": round(float(np.asarray(m["loss"])), 4)},
    }


def run_train():
    jax = _child_jax()

    platform = jax.devices()[0].platform
    if platform == "tpu":
        # memory ladder for one 16GB v5e chip: fp32 master + Adam moments +
        # fp32 grads peak at 16 bytes/param, so llama2-1b (~0.94B) is right
        # at the edge — try it, then step down to the 650M config that fits
        # with headroom (bigger micro-batch, and a no-remat rung that trades
        # the recompute pass for activation memory)
        ladder = [
            ("llama2-1b", 1024, 4, 8, True),
            ("llama2-1b", 1024, 2, 8, True),
            ("llama-650m", 1024, 8, 8, False),
            ("llama-650m", 1024, 8, 8, True),
            ("llama-650m", 1024, 4, 8, True),
        ]
    else:
        ladder = [("tiny", 256, 8, 4, False)]

    import gc

    last_err = None
    for name, seq, micro, steps, remat in ladder:
        try:
            _emit(_measure(name, seq, micro, steps, remat, platform))
            return
        except Exception as e:  # OOM / compile failure → next rung
            last_err = f"{name} micro={micro} remat={remat}: {str(e)[:300]}"
            print(f"bench rung failed: {last_err}", file=sys.stderr)
        # drop the failed rung's buffers before the next attempt (the
        # exception traceback pins the engine's frames until cleared)
        gc.collect()
        jax.clear_caches()
    raise RuntimeError(f"all train rungs failed; last: {last_err}")


# ======================================================================
# rung: serve (FastGen-style TTFT / throughput, SplitFuse A-B)
# ======================================================================
def _drive_serving(eng, prompts, n_clients, reqs_per_client, gen_len, mode,
                   uid_base):
    """Closed-loop clients over the v2 engine at single-forward granularity.

    mode="splitfuse": decode tokens and (chunked) prompt tokens fuse into
    the same forward — the SplitFuse schedule. mode="naive": a waiting
    prompt preempts decoding and prefills to completion first (the
    static-batching behavior the FastGen blog A-Bs against,
    ``blogs/deepspeed-fastgen/README.md:139``).
    """
    import numpy as np

    ttfts, itls = [], []
    submitted, last_tok, gen_count = {}, {}, {}
    live, waiting = {}, []
    pending_tok = {}    # uid -> sampled decode token not yet admitted
    awaiting = set()    # uids with a forward in flight (fresh logits coming)
    ttft_done = set()
    next_req = [0] * n_clients
    finished = evicted = evicted_tokens = total_decoded = stall_guard = 0
    total = n_clients * reqs_per_client

    def submit(c, now):
        i = next_req[c]
        next_req[c] += 1
        uid = uid_base + c * 1000 + i
        waiting.append((uid, c))
        submitted[uid] = now

    def retire(uid, now):
        nonlocal finished
        c = live.pop(uid)
        eng.flush([uid])
        pending_tok.pop(uid, None)
        awaiting.discard(uid)
        finished += 1
        if next_req[c] < reqs_per_client:
            submit(c, now)

    t0 = time.perf_counter()
    for c in range(n_clients):
        submit(c, t0)
    while finished < total:
        now = time.perf_counter()
        # prompts first in naive mode: they preempt and fully prefill
        if mode == "naive" and waiting:
            admit_u, admit_t = [], []
            while waiting:
                uid, c = waiting[0]
                res = eng.check_schedule(admit_u + [uid],
                                         [len(t) for t in admit_t]
                                         + [len(prompts[uid])])
                if uid in res.rejected:
                    break
                waiting.pop(0)
                admit_u.append(uid)
                admit_t.append(prompts[uid])
                live[uid] = c
            if admit_u:
                eng.put(admit_u, admit_t, drain=True)  # decode stalls
                # logits are device-resident and put() is async-dispatch:
                # force completion BEFORE stamping TTFT
                for uid in admit_u:
                    lg = eng.query(uid)
                    if lg is not None:
                        np.asarray(lg)
                now = time.perf_counter()
                for uid in admit_u:
                    ttfts.append(now - submitted[uid])
                    ttft_done.add(uid)
                    last_tok[uid] = now
                    gen_count[uid] = 0
                    awaiting.add(uid)
                stall_guard = 0
                continue
        # consume fresh logits: sample one token per drained live sequence
        for uid in list(live):
            if uid not in awaiting:
                continue
            lg = eng.query(uid)
            if lg is None:
                continue
            awaiting.discard(uid)
            # force the device value BEFORE stamping: the forward is async
            lg = np.asarray(lg)
            now = time.perf_counter()
            if uid not in ttft_done:      # prompt just drained (splitfuse)
                ttfts.append(now - submitted[uid])
                ttft_done.add(uid)
            else:
                itls.append(now - last_tok[uid])
            last_tok[uid] = now
            tok = int(np.argmax(lg))
            gen_count[uid] += 1
            total_decoded += 1
            if gen_count[uid] >= gen_len:
                retire(uid, now)
            else:
                pending_tok[uid] = tok
        put_uids = list(pending_tok)
        put_toks = [[pending_tok[u]] for u in put_uids]
        if mode == "splitfuse":
            while waiting:
                uid, c = waiting[0]
                res = eng.check_schedule(put_uids + [uid],
                                         [len(t) for t in put_toks]
                                         + [len(prompts[uid])])
                if uid in res.rejected:
                    break
                waiting.pop(0)
                put_uids.append(uid)
                put_toks.append(prompts[uid])
                live[uid] = c
                gen_count[uid] = 0
        in_flight = any(d.pending for d in eng.seqs.values())
        if not put_uids and not in_flight:
            stall_guard += 1
            if stall_guard > 3:
                raise RuntimeError(
                    f"serving loop stalled: {len(waiting)} waiting, "
                    f"{len(live)} live, {finished}/{total} done")
            continue
        res = eng.put(put_uids, put_toks, drain=False)
        for uid in res.admission.admitted:
            if uid in pending_tok:
                del pending_tok[uid]
            awaiting.add(uid)
        # KV-pool pressure: a rejected decode token means its sequence can't
        # grow — evict the longest-context live sequence (truncation, like
        # generate()) so decode always progresses; tokens are only counted
        # when a forward actually ran for them
        if (pending_tok and not res.admission.admitted and not in_flight):
            victim = max(live, key=lambda u: eng.seqs[u].n_cached
                         if u in eng.seqs else -1)
            # an evicted request finished with < gen_len tokens: exclude its
            # tokens from the throughput numerator so the A-B arms compare
            # EQUAL work (finished requests x gen_len each) even if their
            # eviction rates differ
            evicted_tokens += gen_count.get(victim, 0)
            retire(victim, now)
            evicted += 1
        stall_guard = 0
    wall = time.perf_counter() - t0
    ttfts.sort()
    itls.sort()

    def pct(xs, p):
        return xs[min(len(xs) - 1, int(p * len(xs)))] if xs else 0.0

    counted = total_decoded - evicted_tokens
    return {"wall_s": round(wall, 3),
            "requests": total,
            "evicted": evicted,
            "tokens_generated": counted,
            "tokens_evicted": evicted_tokens,
            "throughput_tok_s": round(counted / wall, 2),
            "ttft_p50_s": round(pct(ttfts, 0.50), 4),
            "ttft_p95_s": round(pct(ttfts, 0.95), 4),
            "itl_p95_s": round(pct(itls, 0.95), 4)}


def _serve_once(model_name, platform, *, n_clients, reqs_per_client,
                prompt_len, gen_len, budget, block_size, max_context):
    import jax

    from deepspeedsyclsupport_tpu.inference.v2 import InferenceEngineV2
    from deepspeedsyclsupport_tpu.models import build_model, get_config

    cfg = get_config(model_name, max_seq_len=max_context)
    model = build_model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    max_seqs = max(8, 2 * n_clients)
    eng = InferenceEngineV2(model, params,
                            config={"max_tokens_per_batch": budget,
                                    "block_size": block_size,
                                    "max_context": max_context,
                                    "max_sequences": max_seqs,
                                    # fully-committed KV pool: a decode
                                    # token can never be rejected, so the
                                    # driver's eviction path stays cold
                                    "num_blocks": max_seqs
                                    * (max_context // block_size)})
    import numpy as np

    rng = np.random.RandomState(0)

    def mk_prompt():
        return [int(t) for t in rng.randint(1, cfg.vocab_size - 1,
                                            size=prompt_len)]

    # compile prefill + decode in both KV-sharding states outside the
    # timed window (engine-owned warmup; see InferenceEngineV2.warmup)
    eng.warmup()

    results = {}
    for i, mode in enumerate(("naive", "splitfuse")):
        uid_base = (i + 1) * 1_000_000
        prompts = {}
        for c in range(n_clients):
            for r in range(reqs_per_client):
                prompts[uid_base + c * 1000 + r] = mk_prompt()
        results[mode] = _drive_serving(eng, prompts, n_clients,
                                       reqs_per_client, gen_len, mode,
                                       uid_base)
    speedup = (results["splitfuse"]["throughput_tok_s"]
               / max(results["naive"]["throughput_tok_s"], 1e-9))
    sf = results["splitfuse"]
    return {
        "metric": f"serve_decode_tok_per_sec_per_chip_{model_name}",
        "value": sf["throughput_tok_s"],
        "unit": "tokens/s",
        "vs_baseline": round(speedup / REFERENCE_FASTGEN_SPEEDUP, 4),
        "detail": {"platform": platform, "model": model_name,
                   "clients": n_clients, "prompt_len": prompt_len,
                   "gen_len": gen_len, "token_budget": budget,
                   "ttft_p50_s": sf["ttft_p50_s"],
                   "ttft_p95_s": sf["ttft_p95_s"],
                   "itl_p95_s": sf["itl_p95_s"],
                   "splitfuse_vs_naive_speedup": round(speedup, 3),
                   "naive": results["naive"], "splitfuse": sf,
                   "baseline": "SplitFuse-vs-naive effective-throughput "
                               "ratio vs the reference FastGen 2.3x "
                               "headline"},
    }


def run_serve():
    jax = _child_jax()

    platform = jax.devices()[0].platform
    if platform == "tpu":
        ladder = [
            # 16 clients: the reference's SLA benchmark scale
            # (blogs/deepspeed-fastgen/README.md:177, Figure 5)
            dict(model_name="llama-650m", n_clients=16, reqs_per_client=2,
                 prompt_len=512, gen_len=64, budget=768, block_size=64,
                 max_context=1024),
            # 8-client fallback keeps the headline MODEL comparable with
            # earlier rounds if the doubled KV pool does not fit
            dict(model_name="llama-650m", n_clients=8, reqs_per_client=2,
                 prompt_len=512, gen_len=64, budget=768, block_size=64,
                 max_context=1024),
            dict(model_name="tiny", n_clients=8, reqs_per_client=2,
                 prompt_len=512, gen_len=64, budget=768, block_size=64,
                 max_context=1024),
        ]
    else:
        ladder = [
            dict(model_name="tiny", n_clients=4, reqs_per_client=2,
                 prompt_len=48, gen_len=12, budget=64, block_size=16,
                 max_context=128),
        ]
    last_err = None
    for cfg in ladder:
        try:
            _emit(_serve_once(platform=platform, **cfg))
            return
        except Exception as e:
            last_err = f"{cfg['model_name']}: {str(e)[:300]}"
            print(f"serve rung failed: {last_err}", file=sys.stderr)
            jax.clear_caches()
    raise RuntimeError(f"all serve rungs failed; last: {last_err}")


# ======================================================================
# parent orchestration
# ======================================================================
def _parse_lines(text):
    results = []
    for line in (text or "").strip().splitlines():
        try:
            parsed = json.loads(line)
            if isinstance(parsed, dict) and "metric" in parsed:
                results.append(parsed)
        except json.JSONDecodeError:
            continue
    return results


def _spawn(rung, timeout, env_overrides):
    """Run one rung child. Returns (results, err) — BOTH can be non-empty: a
    child that banked some JSON lines and then died/hung keeps its partial
    results AND reports the failure."""
    env = dict(os.environ)
    env[RUNG_ENV] = rung
    env.update(env_overrides)
    try:
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              capture_output=True, text=True, timeout=timeout,
                              env=env)
    except subprocess.TimeoutExpired as e:
        out = e.stdout
        if isinstance(out, bytes):
            out = out.decode("utf-8", "replace")
        return _parse_lines(out), f"{rung}: timeout after {timeout}s"
    results = _parse_lines(proc.stdout)
    if proc.returncode != 0:
        tail = ((proc.stderr or "") + (proc.stdout or ""))[-1500:]
        return results, f"{rung}: rc={proc.returncode}: {tail}"
    if not results:
        tail = ((proc.stderr or "") + (proc.stdout or ""))[-1500:]
        return results, f"{rung}: no metric emitted: {tail}"
    return results, None


CPU_ENV = {"JAX_PLATFORMS": "cpu", "DSTPU_ACCELERATOR": "cpu"}


def _resilient_probe(deadline, budget_frac=0.25):
    """Probe with escalating timeouts across a bounded slice of the bench
    window (VERDICT r3 #1: one 180s shot wasted three rounds of windows).
    Returns (platform, per-attempt diagnosis list)."""
    attempts = []
    budget = min(600.0, max(120.0,
                            (deadline - time.monotonic()) * budget_frac))
    t_start = time.monotonic()
    for to in (45, 90, 180, 300):
        if time.monotonic() - t_start > budget:
            attempts.append({"outcome": "probe budget exhausted",
                             "budget_s": round(budget, 0)})
            break
        t0 = time.monotonic()
        res, err = _spawn("probe", to, {})
        elapsed = round(time.monotonic() - t0, 1)
        if res:
            plat = res[0]["detail"].get("platform", "cpu")
            attempts.append({"timeout_s": to, "elapsed_s": elapsed,
                             "outcome": plat})
            # a clean answer (tpu OR an explicit cpu fallback) is
            # authoritative — only hangs/timeouts justify another attempt
            return plat, attempts
        attempts.append({"timeout_s": to, "elapsed_s": elapsed,
                         "outcome": (err or "no output").split("\n")[0][:160]})
        time.sleep(10)
    return "cpu", attempts


def main():
    deadline = time.monotonic() + float(
        os.environ.get("DSTPU_BENCH_DEADLINE", 3300))
    all_results, errors = [], []

    platform, probe_attempts = _resilient_probe(deadline)
    if probe_attempts and probe_attempts[-1].get("outcome") not in (
            "tpu", "cpu"):
        errors.append(f"probe: {probe_attempts[-1]['outcome']}")

    # (rung, timeout, env, retry-on-cpu-if-tpu-attempt-fails).
    # kernels_micro FIRST on TPU: even a window that collapses right after
    # still banks compiled-kernel evidence.
    if platform == "tpu":
        plan = [("kernels_micro", 400, {}, False),
                ("kernels", 700, {}, False),
                ("train", 1500, {}, True),
                ("serve", 900, {}, True)]
    else:
        plan = [("serve", 500, CPU_ENV, False),
                ("train", 700, CPU_ENV, False)]

    degraded = False
    for rung, timeout, env, cpu_retry in plan:
        remaining = deadline - time.monotonic()
        if remaining < 60:
            errors.append(f"{rung}: skipped (deadline)")
            continue
        if degraded and not env:
            env, cpu_retry = CPU_ENV, False
            if rung.startswith("kernels"):
                errors.append(f"{rung}: skipped (TPU degraded)")
                continue
        results, err = _spawn(rung, min(timeout, remaining), env)
        for r in results:
            _emit(r)
        all_results.extend(results)
        if err:
            errors.append(err)
            if not env:  # a TPU attempt failed
                # only a TIMEOUT implicates the platform (hung tunnel) —
                # a deterministic rung failure (rc!=0) must not cost the
                # remaining rungs their TPU window
                if "timeout" in err:
                    degraded = True
                if cpu_retry and deadline - time.monotonic() > 120:
                    results, err2 = _spawn(
                        rung, min(600, deadline - time.monotonic()), CPU_ENV)
                    for r in results:
                        _emit(r)
                    all_results.extend(results)
                    if err2:
                        errors.append(err2)

    # final aggregated headline: the train number if we have one, else
    # serve, else the best kernel line — with every rung under detail.rungs
    def pick(prefix):
        for r in all_results:
            if r["metric"].startswith(prefix):
                return r
        return None

    # late tunnel window: if everything ran on CPU, spend remaining time on
    # one more probe + the kernel micro-rung so a tunnel that came up
    # mid-bench still yields real-TPU evidence
    if platform != "tpu" and deadline - time.monotonic() > 360:
        res, err = _spawn("probe", 120, {})
        late_plat = res[0]["detail"].get("platform") if res else None
        probe_attempts.append({"timeout_s": 120, "late": True,
                               "outcome": late_plat or
                               (err or "no output").split("\n")[0][:160]})
        if late_plat == "tpu":
            results, err2 = _spawn("kernels_micro",
                                   min(400, deadline - time.monotonic()), {})
            for r in results:
                _emit(r)
            all_results.extend(results)
            if err2:
                errors.append(err2)

    head = pick("train") or pick("serve") or pick("kernel")
    if head is None:
        _emit({"metric": "train_tokens_per_sec_per_chip", "value": 0.0,
               "unit": "tokens/s", "vs_baseline": 0.0,
               "detail": {"platform": "none",
                          "probe_attempts": probe_attempts,
                          "errors": [e[-300:] for e in errors]}})
        return
    # prefer a REAL-TPU line as the headline over a CPU line of an
    # earlier-preferred rung (CPU train numbers are not the perf story)
    tpu_lines = [r for r in all_results
                 if r.get("detail", {}).get("platform") == "tpu"]
    if head.get("detail", {}).get("platform") != "tpu" and tpu_lines:
        for prefix in ("train", "serve", "kernel"):
            cand = next((r for r in tpu_lines
                         if r["metric"].startswith(prefix)), None)
            if cand is not None:
                head = cand
                break
    rest = [r for r in all_results if r is not head]
    head = dict(head)
    head["detail"] = dict(head.get("detail", {}))
    head["detail"]["rungs"] = rest
    head["detail"]["probe_attempts"] = probe_attempts
    if errors:
        head["detail"]["rung_errors"] = [e[-300:] for e in errors]
    _emit(head)


if __name__ == "__main__":
    rung = os.environ.get(RUNG_ENV)
    if rung == "probe":
        run_probe()
    elif rung == "kernels_micro":
        run_kernels_micro()
    elif rung == "kernels":
        run_kernels()
    elif rung == "train":
        run_train()
    elif rung == "serve":
        run_serve()
    else:
        main()
        sys.exit(0)
