"""Headline benchmark — training tokens/sec/chip on the flagship Llama-family model.

Runs on whatever single accelerator is present (driver: one real TPU chip) and
prints ONE JSON line. ``vs_baseline`` compares achieved model-FLOPs utilization to
the reference's best published sustained utilization — DeepSpeed-Ulysses' 175
TFLOPs/GPU on A100 = 54% of bf16 peak (``blogs/deepspeed-ulysses/README.md:82``,
mirrored in BASELINE.md) — i.e. vs_baseline > 1 means we sustain a larger fraction
of our chip's peak than the reference does of its chip's.

Resilience contract (round-1 postmortem: BENCH_r01.json rc=1 on TPU backend
init): this script ALWAYS exits 0 and ALWAYS prints one valid JSON line. The
parent process runs the actual benchmark in a child subprocess; if the child
dies on backend init it is retried once (transient tunnel failures) and then
re-run with ``JAX_PLATFORMS=''`` (auto-select) and ``JAX_PLATFORMS=cpu``
fallbacks, degrading the platform rather than losing the round's number.
"""
import json
import os
import subprocess
import sys
import time

# bf16 peak FLOPs by platform (per chip)
PEAKS = {"tpu": 197e12,   # TPU v5e
         "cpu": 1e12}     # nominal, for smoke runs off-TPU
REFERENCE_MFU = 0.54       # Ulysses 175/312 TFLOPs on A100 (BASELINE.md)
CHILD_ENV = "DSTPU_BENCH_CHILD"


def model_flops_per_token(cfg):
    """6·N_active for the matmuls + attention quadratic term."""
    n_active = cfg.param_count()
    if cfg.num_experts > 0:
        dense_mlp = 3 * cfg.hidden_size * cfg.intermediate_size * cfg.num_layers
        n_active -= dense_mlp * (cfg.num_experts - cfg.num_experts_per_tok)
    attn = 12 * cfg.num_layers * cfg.hidden_size  # ≈ per token at seq S: *S below
    return 6 * n_active, attn


def _measure(name, seq, micro_bs, steps, remat, platform):
    """One bench rung: build → warmup/compile → timed steps → metrics dict.
    Raises on OOM/compile failure; the caller's ladder steps down."""
    import jax
    import numpy as np

    import deepspeedsyclsupport_tpu as ds
    from deepspeedsyclsupport_tpu.comm.topology import reset_world_topology
    from deepspeedsyclsupport_tpu.models import build_model, get_config

    cfg = get_config(name, remat=remat, max_seq_len=seq)
    reset_world_topology()
    topo = ds.build_topology(dp=1)
    model = build_model(cfg)
    config = {
        "train_batch_size": micro_bs,
        "train_micro_batch_size_per_gpu": micro_bs,
        "optimizer": {"type": "adam", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, topology=topo)
    batch = {"input_ids": jax.random.randint(jax.random.PRNGKey(0),
                                             (micro_bs, seq), 0,
                                             cfg.vocab_size)}
    # warmup/compile. NOTE: sync via value fetch (float), NOT block_until_ready —
    # on the axon remote-TPU platform block_until_ready returns before the
    # dispatch chain finishes; fetching the value is the reliable barrier.
    for _ in range(2):
        m = engine.train_batch(batch)
    float(np.asarray(jax.device_get(m["loss"])))

    t0 = time.perf_counter()
    for _ in range(steps):
        m = engine.train_batch(batch)
    float(np.asarray(jax.device_get(m["loss"])))
    dt = time.perf_counter() - t0

    tokens = steps * micro_bs * seq
    tok_per_sec = tokens / dt
    f_matmul, f_attn = model_flops_per_token(cfg)
    flops_per_token = f_matmul + f_attn * seq
    achieved = tok_per_sec * flops_per_token
    mfu = achieved / PEAKS.get(platform, PEAKS["cpu"])
    return {
        "metric": f"train_tokens_per_sec_per_chip_{name}_seq{seq}",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / REFERENCE_MFU, 4),
        "detail": {"platform": platform, "mfu": round(mfu, 4),
                   "tflops": round(achieved / 1e12, 2),
                   "micro_bs": micro_bs, "remat": remat,
                   "loss": round(float(np.asarray(m["loss"])), 4)},
    }


def run_bench():
    import jax

    # The axon sitecustomize force-sets jax_platforms at interpreter start,
    # so the JAX_PLATFORMS env var alone cannot steer the child; re-pin via
    # jax.config before any backend initializes.
    plat_override = os.environ.get("JAX_PLATFORMS")
    if plat_override:
        jax.config.update("jax_platforms", plat_override)

    platform = jax.devices()[0].platform
    if platform == "tpu":
        # memory ladder for one 16GB v5e chip: fp32 master + Adam moments +
        # fp32 grads peak at 16 bytes/param, so llama2-1b (~0.94B) is right
        # at the edge — try it, then step down to the 650M config that fits
        # with headroom (bigger micro-batch, and a no-remat rung that trades
        # the recompute pass for activation memory)
        ladder = [
            ("llama2-1b", 1024, 4, 8, True),
            ("llama2-1b", 1024, 2, 8, True),
            ("llama-650m", 1024, 8, 8, False),
            ("llama-650m", 1024, 8, 8, True),
            ("llama-650m", 1024, 4, 8, True),
        ]
    else:
        ladder = [("tiny", 256, 8, 4, False)]

    import gc

    last_err = None
    for name, seq, micro, steps, remat in ladder:
        try:
            result = _measure(name, seq, micro, steps, remat, platform)
            print(json.dumps(result))
            return
        except Exception as e:  # OOM / compile failure → next rung
            last_err = f"{name} micro={micro} remat={remat}: {str(e)[:300]}"
            print(f"bench rung failed: {last_err}", file=sys.stderr)
        # drop the failed rung's buffers before the next attempt (the
        # exception traceback pins the engine's frames until cleared)
        gc.collect()
        jax.clear_caches()
    raise RuntimeError(f"all bench rungs failed; last: {last_err}")


def _spawn(env_overrides, timeout=1500):
    env = dict(os.environ)
    env[CHILD_ENV] = "1"
    env.update(env_overrides)
    try:
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              capture_output=True, text=True, timeout=timeout,
                              env=env)
    except subprocess.TimeoutExpired as e:
        return None, f"timeout: {e}"
    for line in reversed((proc.stdout or "").strip().splitlines()):
        try:
            parsed = json.loads(line)
            if isinstance(parsed, dict) and "metric" in parsed:
                return line, None
        except json.JSONDecodeError:
            continue
    tail = ((proc.stderr or "") + (proc.stdout or ""))[-2000:]
    return None, f"rc={proc.returncode}: {tail}"


def main():
    # per-attempt timeouts: a HUNG tpu tunnel (observed: compute blocks
    # forever while jax.devices() succeeds) must not eat the whole bench
    # window before the cpu fallback gets its turn
    attempts = [
        ({}, 1500),                       # native platform (TPU when present)
        ({}, 1200),                       # once more: transient blips
        # guaranteed-available degraded run (accelerator seam pinned too so
        # topology building never probes the dead tunnel)
        ({"JAX_PLATFORMS": "cpu", "DSTPU_ACCELERATOR": "cpu"}, 900),
    ]
    errors = []
    for i, (overrides, timeout) in enumerate(attempts):
        if (i == 1 and errors and errors[-1]
                and errors[-1].startswith("timeout")):
            # a HUNG tunnel times out identically on retry — go straight to
            # the guaranteed cpu rung instead of burning another window
            errors.append("skipped retry after timeout")
            continue
        line, err = _spawn(overrides, timeout)
        if line is not None:
            print(line)
            return
        errors.append(err)
    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip",
        "value": 0.0,
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "detail": {"platform": "none", "error": (errors[-1] or "")[-500:]},
    }))


if __name__ == "__main__":
    if os.environ.get(CHILD_ENV):
        run_bench()
    else:
        main()
        sys.exit(0)
