"""Benchmark harness — rungs run cheapest-first, one JSON line per success.

Rungs (each an isolated child process so a hang/OOM in one cannot eat the
others' window):
  probe    — which platform actually answers (the axon TPU tunnel can hang)
  kernels  — COMPILED (non-interpret) Pallas parity + throughput microbench:
             flash fwd / fwd+bwd, ragged paged prefill, paged decode, each
             against its jnp oracle (TPU only — interpret numbers are not
             kernel evidence)
  train    — the training-MFU ladder on the flagship Llama-family model
  serve    — FastGen-style serving benchmark on the v2 ragged engine:
             closed-loop clients, p50/p95 TTFT, decode tokens/sec/chip, and
             a SplitFuse-on/off A-B (reference headline: 2.3x effective
             throughput, ``blogs/deepspeed-fastgen/README.md:28,139``)

The FINAL line aggregates every rung result under ``detail.rungs`` so a
parser that keeps only the last JSON line still sees everything.
``vs_baseline`` semantics per rung are in each line's ``detail.baseline``.

Resilience contract (round-1/2 postmortems: BENCH_r01 rc=1 on backend init,
BENCH_r02 silently degraded to CPU): this script ALWAYS exits 0 and ALWAYS
prints at least one valid JSON line; TPU rungs that hang or die fall back to
CPU where that still yields a meaningful regression number (train/serve),
and the platform is recorded honestly in every line.
"""
import json
import os
import subprocess
import sys
import time

# the model stack uses modern jax spellings; on an older jax the opt-in
# compat shims (utils/jax_compat.py) graft them on. Must be set before any
# deepspeedsyclsupport_tpu import (children import inside their rung fns).
os.environ.setdefault("DSTPU_JAX_COMPAT", "1")

# bf16 peak FLOPs and HBM bandwidth by platform (per chip)
PEAKS = {"tpu": 197e12,   # TPU v5e
         "cpu": 1e12}     # nominal, for smoke runs off-TPU
HBM_GBPS = {"tpu": 819.0, "cpu": 50.0}
REFERENCE_MFU = 0.54       # Ulysses 175/312 TFLOPs on A100 (BASELINE.md)
REFERENCE_FASTGEN_SPEEDUP = 2.3  # FastGen effective-throughput headline
RUNG_ENV = "DSTPU_BENCH_RUNG"


def _emit(result):
    print(json.dumps(result), flush=True)


class _ScenarioTimeout(RuntimeError):
    """A single scenario (one load point / A-B arm) overran its budget.
    Raised from inside the driving loop so the caller can flush whatever
    the sweep completed so far instead of losing the whole rung (the r05
    rc=124 failure mode: the bench died with everything buffered)."""


def _attn_overrides(attn):
    """Serving-config overrides for an explicit attention impl (the XLA
    fallback rungs); {} keeps the registry's auto selection."""
    return {"prefill_attn": attn, "decode_attn": attn} if attn else {}


def _child_jax():
    """Import jax honouring a JAX_PLATFORMS override — the axon
    sitecustomize force-pins jax_platforms at interpreter start, so the env
    var alone cannot steer the child; re-pin via jax.config before any
    backend initializes."""
    import jax

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    return jax


def _sync(x):
    """Reliable device barrier: fetch a value. On the axon remote-TPU
    platform block_until_ready can return before the dispatch chain
    finishes; a value fetch is the true barrier."""
    import numpy as np

    return float(np.asarray(x).reshape(-1)[0])


# ======================================================================
# rung: probe
# ======================================================================
def run_probe():
    jax = _child_jax()
    dev = jax.devices()[0]
    _emit({"metric": "probe", "value": len(jax.devices()), "unit": "devices",
           "vs_baseline": 1.0, "detail": {"platform": dev.platform}})


# ======================================================================
# rung: kernels (compiled Pallas vs jnp oracle — TPU only)
# ======================================================================
def _rel_err(got, want):
    import numpy as np

    g, w = np.asarray(got, np.float32), np.asarray(want, np.float32)
    return float(np.max(np.abs(g - w)) / (np.max(np.abs(w)) + 1e-9))


def _bench_chain(fn_one, x0, extra_args, iters):
    """Per-iteration device time of ``fn_one(x, *extra) -> x'`` measured as
    ``iters`` data-dependent applications inside ONE jitted fori_loop — a
    single dispatch, so remote-tunnel per-call latency (several ms on the
    axon path, enough to swamp a sub-ms kernel) cancels out. The chained
    data dependency defeats CSE/DCE. The one-dispatch floor is measured
    separately and subtracted."""
    import jax
    from jax import lax

    def chained(x, extra):
        return lax.fori_loop(0, iters, lambda i, xx: fn_one(xx, *extra), x)

    def best_of(f, n=3):
        _sync(f(x0, extra_args))    # compile/warm
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            _sync(f(x0, extra_args))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    total = best_of(jax.jit(chained))
    # dispatch floor: same structure, 1 iteration
    floor = best_of(jax.jit(lambda x, extra: fn_one(x, *extra)))
    if total <= floor or iters < 2:
        # tunnel jitter swamped the kernel — the difference of two noisy
        # samples is meaningless; report the per-dispatch bound honestly
        # instead of clamping to an absurd number
        return floor, "dispatch_bound"
    return (total - floor) / (iters - 1), "chained"


def _dense_attn_ref(q, k, v, causal=True):
    import jax
    import jax.numpy as jnp
    import numpy as np

    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) / np.sqrt(d)
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        m = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
        s = jnp.where(m, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, vf).astype(q.dtype)


def _make_atoms(lens, bq, block_size, h, kvh, d, key, dtype):
    """Synthetic ragged prefill batch: one atom per bq-row chunk of each
    sequence, disjoint block tables, full-prefill positions."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    bps = max(-(-ln // block_size) for ln in lens)
    pos0, qlen, atom_tbl = [], [], []
    next_blk = 0
    for ln in lens:
        nb = -(-ln // block_size)
        row = list(range(next_blk, next_blk + nb)) + [0] * (bps - nb)
        next_blk += nb
        for a0 in range(0, ln, bq):
            pos0.append(a0)
            qlen.append(min(bq, ln - a0))
            atom_tbl.append(row)
    slots = next_blk * block_size
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (len(pos0), bq, h, d), dtype)
    k = jax.random.normal(ks[1], (slots, kvh, d), dtype)
    v = jax.random.normal(ks[2], (slots, kvh, d), dtype)
    return (q, k, v, jnp.asarray(np.asarray(atom_tbl, np.int32)),
            jnp.asarray(pos0, dtype=jnp.int32),
            jnp.asarray(qlen, dtype=jnp.int32))


def run_kernels_micro():
    """<60s compiled-kernel evidence: ONE Pallas kernel (flash fwd), f32
    parity at small shape + bf16 throughput at production shape. Runs FIRST
    on TPU so even a brief tunnel window banks a compiled-kernel line
    (VERDICT r3 #1: three rounds with zero real-TPU evidence)."""
    jax = _child_jax()
    import jax.numpy as jnp

    from deepspeedsyclsupport_tpu.ops import flash_attention as fa

    platform = jax.devices()[0].platform
    smoke = bool(os.environ.get("DSTPU_BENCH_SMOKE"))
    if platform != "tpu" and not smoke:
        print("kernels_micro requires TPU; skipping", file=sys.stderr)
        return
    peak = PEAKS[platform]
    key = jax.random.PRNGKey(0)
    t0 = time.perf_counter()

    ks = jax.random.split(key, 3)
    q32 = jax.random.normal(ks[0], (1, 256, 4, 64), jnp.float32)
    got = jax.jit(lambda *a: fa.flash_attention(*a, causal=True))(
        q32, q32, q32)
    want = jax.jit(_dense_attn_ref)(q32, q32, q32)
    err = _rel_err(got, want)

    b, s, h, d = (1, 256, 2, 64) if smoke else (4, 2048, 16, 128)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, s, h, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, s, h, d), jnp.bfloat16)
    dt, how = _bench_chain(
        lambda x, k, v: fa.flash_attention(x, k, v, causal=True),
        q, (k, v), 4 if smoke else 10)
    tflops = 4 * b * h * s * s * d * 0.5 / dt / 1e12
    _emit({"metric": "kernel_micro_flash_fwd", "value": round(tflops, 2),
           "unit": "TFLOP/s",
           "vs_baseline": round(tflops * 1e12 / peak / REFERENCE_MFU, 4),
           "detail": {"platform": platform, "shape": [b, s, h, d],
                      "dtype": "bfloat16", "parity_max_rel_err": err,
                      "parity_ok": err < 5e-2, "timing": how,
                      "wall_s": round(time.perf_counter() - t0, 1),
                      "baseline": "fraction of chip peak vs reference "
                                  "54% MFU"}})


def run_kernels():
    jax = _child_jax()
    import functools

    import jax.numpy as jnp
    import numpy as np

    from deepspeedsyclsupport_tpu.ops import flash_attention as fa
    from deepspeedsyclsupport_tpu.ops import paged_attention as pa

    platform = jax.devices()[0].platform
    smoke = bool(os.environ.get("DSTPU_BENCH_SMOKE"))
    if platform != "tpu" and not smoke:
        print("kernels rung requires TPU (interpret mode is not kernel "
              "evidence); skipping", file=sys.stderr)
        return
    interp = platform != "tpu"  # smoke mode only: validate the rung's flow
    peak, bw = PEAKS[platform], HBM_GBPS[platform]
    key = jax.random.PRNGKey(0)

    # -------- flash attention: parity (f32, with grads) ------------------
    ks = jax.random.split(key, 4)
    b, s, h, d = 2, 512, 4, 64
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, h, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, h, d), jnp.float32)
    got = jax.jit(lambda *a: fa.flash_attention(*a, causal=True))(q, k, v)
    want = jax.jit(_dense_attn_ref)(q, k, v)
    fwd_err = _rel_err(got, want)

    def loss(f):
        return lambda q, k, v: (f(q, k, v) * v).astype(jnp.float32).sum()

    g_got = jax.jit(jax.grad(loss(
        lambda *a: fa.flash_attention(*a, causal=True)), (0, 1, 2)))(q, k, v)
    g_want = jax.jit(jax.grad(loss(_dense_attn_ref), (0, 1, 2)))(q, k, v)
    bwd_err = max(_rel_err(a_, b_) for a_, b_ in zip(g_got, g_want))

    # -------- flash attention: throughput (bf16) -------------------------
    b, s, h, d = (1, 256, 2, 64) if smoke else (4, 2048, 16, 128)
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, s, h, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, s, h, d), jnp.bfloat16)
    dt, how = _bench_chain(
        lambda x, k, v: fa.flash_attention(x, k, v, causal=True),
        q, (k, v), 20)
    flops_fwd = 4 * b * h * s * s * d * 0.5  # 2 matmuls, causal half
    tflops = flops_fwd / dt / 1e12
    _emit({"metric": "kernel_flash_fwd", "value": round(tflops, 2),
           "unit": "TFLOP/s",
           "vs_baseline": round(tflops * 1e12 / peak / REFERENCE_MFU, 4),
           "detail": {"platform": platform, "shape": [b, s, h, d],
                      "dtype": "bfloat16", "parity_max_rel_err": fwd_err,
                      "parity_ok": fwd_err < 5e-2, "timing": how,
                      "baseline": "fraction of chip peak vs reference 54% MFU"}})

    bwd_one = jax.grad(
        lambda q, k, v: fa.flash_attention(q, k, v, causal=True)
        .astype(jnp.float32).sum(), (0, 1, 2))

    def bwd_step(x, k, v):
        # fold dk/dv into the carry with an epsilon term so the dk/dv
        # pallas_call stays LIVE (chaining dq alone lets XLA dead-code the
        # second backward kernel and inflates the reported TFLOP/s)
        dq, dk, dv = bwd_one(x, k, v)
        eps = (dk.astype(jnp.float32).sum()
               + dv.astype(jnp.float32).sum()) * jnp.float32(1e-30)
        return (dq.astype(jnp.float32) + eps).astype(x.dtype)

    dt, how = _bench_chain(bwd_step, q, (k, v), 10)
    flops_fb = flops_fwd * 3.5  # grad call = fwd (2 matmuls) + bwd (5)
    tflops = flops_fb / dt / 1e12
    _emit({"metric": "kernel_flash_bwd", "value": round(tflops, 2),
           "unit": "TFLOP/s",
           "vs_baseline": round(tflops * 1e12 / peak / REFERENCE_MFU, 4),
           "detail": {"platform": platform, "shape": [b, s, h, d],
                      "dtype": "bfloat16", "parity_max_rel_err": bwd_err,
                      "parity_ok": bwd_err < 5e-2, "timing": how,
                      "baseline": "fraction of chip peak vs reference 54% MFU"}})

    # -------- ragged paged prefill: parity (f32, GQA) --------------------
    at = _make_atoms([96, 64, 33], 32, 16, 4, 2, 32, jax.random.PRNGKey(1),
                     jnp.float32)
    kern = functools.partial(pa.ragged_prefill_attention_pallas,
                             block_size=16, interpret=interp)
    got = jax.jit(kern)(*at)
    want = jax.jit(functools.partial(pa.ragged_prefill_attention_reference,
                                     block_size=16))(*at)
    valid = np.asarray(jnp.arange(32)[None, :] < at[5][:, None])
    pre_err = _rel_err(np.asarray(got)[valid], np.asarray(want)[valid])

    # -------- ragged paged prefill: throughput (bf16) --------------------
    lens = ([128, 64] if smoke
            else [2048, 1536, 1024, 1024, 512, 512, 256, 256])
    at = _make_atoms(lens, 128, 64, 16, 16, 128, jax.random.PRNGKey(2),
                     jnp.bfloat16)
    pre_one = functools.partial(pa.ragged_prefill_attention_pallas,
                                block_size=64, interpret=interp)
    dt, how = _bench_chain(lambda x, *rest: pre_one(x, *rest).astype(x.dtype),
                           at[0], tuple(at[1:]), 4 if smoke else 10)
    flops = sum(2 * 16 * 128 * ln * ln for ln in lens)  # causal half of 4
    tflops = flops / dt / 1e12
    _emit({"metric": "kernel_ragged_prefill", "value": round(tflops, 2),
           "unit": "TFLOP/s",
           "vs_baseline": round(tflops * 1e12 / peak / REFERENCE_MFU, 4),
           "detail": {"platform": platform, "seq_lens": lens,
                      "dtype": "bfloat16", "parity_max_rel_err": pre_err,
                      "parity_ok": pre_err < 5e-2, "timing": how,
                      "baseline": "fraction of chip peak vs reference 54% MFU"}})

    # -------- paged decode: parity (f32) then bandwidth (bf16) -----------
    def decode_setup(slots, bps, block, h, kvh, d, dtype, seed):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        nb = slots * bps
        q = jax.random.normal(ks[0], (slots, h, d), dtype)
        kc = jax.random.normal(ks[1], (nb * block, kvh, d), dtype)
        vc = jax.random.normal(ks[2], (nb * block, kvh, d), dtype)
        tables = jnp.arange(nb, dtype=jnp.int32).reshape(slots, bps)
        lens_ = jnp.full((slots,), bps * block, jnp.int32)
        return q, kc, vc, tables, lens_

    args = decode_setup(4, 3, 16, 4, 2, 32, jnp.float32, 3)
    got = jax.jit(functools.partial(pa.paged_decode_attention_pallas,
                                    block_size=16, interpret=interp))(*args)
    want = jax.jit(functools.partial(pa.paged_decode_attention_reference,
                                     block_size=16))(*args)
    dec_err = _rel_err(got, want)

    slots, bps, block, h, d = ((4, 2, 16, 2, 64) if smoke
                               else (64, 16, 64, 16, 128))
    args = decode_setup(slots, bps, block, h, h, d, jnp.bfloat16, 4)
    dec_one = functools.partial(pa.paged_decode_attention_pallas,
                                block_size=block, interpret=interp)
    dt, how = _bench_chain(lambda x, *rest: dec_one(x, *rest).astype(x.dtype),
                           args[0], tuple(args[1:]), 4 if smoke else 20)
    bytes_moved = slots * bps * block * h * d * 2 * 2  # K+V, bf16
    gbps = bytes_moved / dt / 1e9
    _emit({"metric": "kernel_paged_decode", "value": round(gbps, 1),
           "unit": "GB/s",
           "vs_baseline": round(gbps / bw, 4),
           "detail": {"platform": platform,
                      "slots": slots, "context": bps * block,
                      "dtype": "bfloat16", "parity_max_rel_err": dec_err,
                      "parity_ok": dec_err < 5e-2, "timing": how,
                      "baseline": "fraction of HBM peak bandwidth "
                                  "(decode attention is BW-bound)"}})


# ======================================================================
# rung: train (MFU ladder)
# ======================================================================
def model_flops_per_token(cfg):
    """6·N_active for the matmuls + attention quadratic term."""
    n_active = cfg.param_count()
    if cfg.num_experts > 0:
        dense_mlp = 3 * cfg.hidden_size * cfg.intermediate_size * cfg.num_layers
        n_active -= dense_mlp * (cfg.num_experts - cfg.num_experts_per_tok)
    attn = 12 * cfg.num_layers * cfg.hidden_size  # ≈ per token at seq S: *S below
    return 6 * n_active, attn


def _measure(name, seq, micro_bs, steps, remat, platform,
             attn_impl="auto", topo_axes=None):
    """One bench rung: build → warmup/compile → timed steps → metrics dict.
    Raises on OOM/compile failure; the caller's ladder steps down.

    Every rung now runs under telemetry with ``telemetry.mfu`` on: the
    warmup's third step is the captured clean-step window (outside the
    timed loop, so the one deliberately-synced step never pollutes
    tokens/s) and ``detail.mfu`` carries the full step-time attribution
    ledger — achieved MFU, the peak→roofline→measured waterfall and the
    per-region bound-by verdicts (docs/observability.md "MFU ledger")."""
    import shutil
    import tempfile

    # scratch telemetry/trace dir for this rung only: the ledger dict is
    # extracted before return, so the artifacts never outlive the attempt
    # (the OOM ladder retries would otherwise pile dirs up in /tmp)
    tdir = tempfile.mkdtemp(prefix="dstpu_bench_mfu_")
    try:
        return _measure_impl(name, seq, micro_bs, steps, remat, platform,
                             attn_impl, topo_axes, tdir)
    finally:
        shutil.rmtree(tdir, ignore_errors=True)


def _measure_impl(name, seq, micro_bs, steps, remat, platform, attn_impl,
                  topo_axes, tdir):
    import jax
    import numpy as np

    import deepspeedsyclsupport_tpu as ds
    from deepspeedsyclsupport_tpu.comm.topology import reset_world_topology
    from deepspeedsyclsupport_tpu.models import build_model, get_config

    cfg = get_config(name, remat=remat, max_seq_len=seq,
                     attn_impl=attn_impl)
    reset_world_topology()
    topo = ds.build_topology(**(topo_axes or {"dp": 1}))
    model = build_model(cfg)
    config = {
        "train_batch_size": micro_bs,
        "train_micro_batch_size_per_gpu": micro_bs,
        "optimizer": {"type": "adam", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        # the ROADMAP MFU levers, explicit in the ENGINE config (not just
        # the model flag): remat via the activation_checkpointing section;
        # buffer donation is the fused train path's default and is VERIFIED
        # below by the analysis donation audit — a missed donation is a
        # silent HBM doubling that shrinks the ladder's feasible rungs
        "activation_checkpointing": {"enabled": remat},
        "steps_per_print": 10_000,
        "telemetry": {"enabled": True,
                      "output_dir": tdir,
                      "heartbeat": {"enabled": False},
                      "mfu": {"enabled": True, "step": 3}},
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, topology=topo)
    batch = {"input_ids": jax.random.randint(jax.random.PRNGKey(0),
                                             (micro_bs, seq), 0,
                                             cfg.vocab_size)}
    # 3 warmup steps: compile (1), warm (2), MFU window capture (3 — the
    # one synced step, deliberately before the timed loop). If step 3
    # recompiled, the engine re-arms the capture — DRAIN it here (bounded)
    # so the synced window never lands inside the timed loop below.
    for _ in range(3):
        m = engine.train_batch(batch)
    for _ in range(4):
        if not getattr(engine, "_mfu_pending", False):
            break
        m = engine.train_batch(batch)
    _sync(m["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        m = engine.train_batch(batch)
    _sync(m["loss"])
    dt = time.perf_counter() - t0

    tokens = steps * micro_bs * seq
    tok_per_sec = tokens / dt
    f_matmul, f_attn = model_flops_per_token(cfg)
    flops_per_token = f_matmul + f_attn * seq
    achieved = tok_per_sec * flops_per_token
    mfu = achieved / PEAKS.get(platform, PEAKS["cpu"])
    # donation audit (analysis/donation.py) on the exact compiled step we
    # just timed — re-lowering is a compile-cache hit. Outside the timed
    # window; best-effort (the bench contract: never die on telemetry).
    try:
        rep = engine.graph_report(analyzers=("donation",))["donation"]
        donation = {"ok": rep.ok, "donated": len(rep.donated),
                    "missed": len(rep.not_donated),
                    "wasted_bytes": rep.wasted_bytes}
    except Exception as e:
        donation = {"ok": None, "error": str(e)[:200]}
    # the MFU ledger from the captured window (same never-die contract)
    try:
        ledger = engine.mfu_ledger()
        ledger.pop("window", None)
    except Exception as e:
        ledger = {"error": str(e)[:200]}
    try:
        engine.telemetry.close("bench")
    except Exception:
        pass
    return {
        "metric": f"train_tokens_per_sec_per_chip_{name}_seq{seq}",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / REFERENCE_MFU, 4),
        "detail": {"platform": platform,
                   # detail.mfu is the LEDGER (dict) from this round on;
                   # the headline scalar (tok/s-derived fraction of chip
                   # peak, the pre-ledger detail.mfu) moves to mfu_headline
                   "mfu": ledger,
                   "mfu_headline": round(mfu, 4),
                   "tflops": round(achieved / 1e12, 2),
                   "micro_bs": micro_bs, "remat": remat,
                   "donation": donation,
                   "attn_impl": attn_impl,
                   "baseline": "achieved MFU vs reference 54% (Ulysses "
                               "175/312 TFLOPs on A100)",
                   "loss": round(float(np.asarray(m["loss"])), 4)},
    }


def run_train():
    jax = _child_jax()

    platform = jax.devices()[0].platform
    if platform == "tpu":
        # memory ladder for one 16GB v5e chip: fp32 master + Adam moments +
        # fp32 grads peak at 16 bytes/param, so llama2-1b (~0.94B) is right
        # at the edge — try it, then step down to the 650M config that fits
        # with headroom (bigger micro-batch, and a no-remat rung that trades
        # the recompute pass for activation memory)
        ladder = [
            ("llama2-1b", 1024, 4, 8, True),
            ("llama2-1b", 1024, 2, 8, True),
            ("llama-650m", 1024, 8, 8, False),
            ("llama-650m", 1024, 8, 8, True),
            ("llama-650m", 1024, 4, 8, True),
        ]
    else:
        ladder = [("tiny", 256, 8, 4, False)]

    import gc

    t_start = time.monotonic()
    # variants must START early enough to FINISH inside the parent's
    # _spawn timeout (1200 s): a variant is a fresh compile (~2 min) +
    # timed steps, so leave ~half the window as headroom — an optional
    # A-B overrunning the child would read as a tunnel timeout upstream
    # and degrade every remaining TPU rung to CPU
    budget = float(os.environ.get("DSTPU_TRAIN_BUDGET", 600))
    last_err = None
    base = None
    for name, seq, micro, steps, remat in ladder:
        try:
            r = _measure(name, seq, micro, steps, remat, platform)
            _emit(r)
            base = (name, seq, micro, steps, remat, r)
            break
        except Exception as e:  # OOM / compile failure → next rung
            last_err = f"{name} micro={micro} remat={remat}: {str(e)[:300]}"
            print(f"bench rung failed: {last_err}", file=sys.stderr)
        # drop the failed rung's buffers before the next attempt (the
        # exception traceback pins the engine's frames until cleared)
        gc.collect()
        jax.clear_caches()
    if base is None:
        raise RuntimeError(f"all train rungs failed; last: {last_err}")
    # A-B the big perf levers inside the remaining budget: attention impl
    # (flash Pallas vs XLA's fused attention at this seq) and remat off
    # (recompute pass vs activation memory). The parent headlines the BEST
    # train line, so a faster variant directly moves the round's number.
    if platform == "tpu":
        name, seq, micro, steps, remat, _ = base
        # long context: the reference's 54% MFU bar is a LONG-SEQUENCE
        # (Ulysses) number, and both flash and MFU improve with seq — the
        # seq-4k rung is the apples-to-apples comparison. It runs FIRST
        # only when tokens/step stay equal (micro/4 >= 1); on a memory-edge
        # base (micro < 4) 4096 tokens/step would exceed the base and a
        # likely OOM's wasted compile would eat the other variants' budget
        variants = [("xla_attn", dict(attn_impl="xla"))]
        if micro >= 4:
            variants.insert(0, ("seq4k", dict(seq=4096, micro=micro // 4)))
        else:
            variants.append(("seq4k", dict(seq=4096, micro=1)))
        if remat:
            variants.append(("noremat", dict(remat=False)))
        for tag, kw in variants:
            if time.monotonic() - t_start > budget:
                print("train variant skipped (budget)", file=sys.stderr)
                break
            # free the previous engine's executables/caches BEFORE the
            # next full compile — llama2-1b sits at the edge of the chip
            gc.collect()
            jax.clear_caches()
            try:
                r = _measure(name, kw.get("seq", seq),
                             kw.get("micro", micro), steps,
                             kw.get("remat", remat), platform,
                             attn_impl=kw.get("attn_impl", "auto"))
                r["metric"] += f"_{tag}"  # unique metric per variant
                _emit(r)
            except Exception as e:
                print(f"train variant {tag} failed: {str(e)[:200]}",
                      file=sys.stderr)


# ======================================================================
# rung: train_ring (ring-attention attn_impl A/B under the MFU ledger)
# ======================================================================
def run_train_ring():
    """Ring-attention ``attn_impl`` A/B on a seq-sharded 2-device mesh
    (CPU sim): the inline online-softmax ring (``ring:xla``) vs the
    Pallas-flash per-block path (``ring:flash`` — interpret mode off-TPU,
    so CPU prices dispatch structure, not kernel speed). Both arms run
    under the MFU ledger, so each line's ``detail.mfu`` carries the
    per-region attention time — the A/B the ROADMAP's long-sequence item
    needs before the real-TPU run."""
    jax = _child_jax()

    platform = jax.devices()[0].platform
    if len(jax.devices()) < 2:
        _emit({"metric": "train_ring_skipped", "value": 0.0, "unit": "arms",
               "vs_baseline": 0.0,
               "detail": {"platform": platform,
                          "reason": "needs >= 2 devices for the seq mesh"}})
        return
    for tag, impl, seq, micro, steps in (
            ("xla", "ring:xla", 256, 4, 2),
            ("flash", "ring:flash", 256, 4, 2)):
        try:
            r = _measure("tiny", seq, micro, steps, False, platform,
                         attn_impl=impl, topo_axes={"dp": 1, "sp": 2})
            r["metric"] = f"train_ring_{tag}_tokens_per_sec_per_chip"
            _emit(r)
        except Exception as e:
            print(f"train_ring arm {tag} failed: {str(e)[:300]}",
                  file=sys.stderr)


# ======================================================================
# rung: multichip (pod-scope comm/compute decomposition on the CPU sim)
# ======================================================================
def run_multichip():
    """8-virtual-device ZeRO-3 training with per-rank flight recorders and
    the static collective census, fused by ``monitor/pod.py``, A-B'd
    full-precision vs quantized collectives (ZeRO++ qwZ int8 weight
    all-gather + qgZ int8 grad all-to-all-reduce, ``comm/quantized.py``
    via ``runtime/zeropp.py``): the per-traffic-class
    ``class_bytes_per_step`` ratios and the ``comm_bound_frac`` delta ARE
    the wire-byte proof the ROADMAP's quantized-collectives item asks for
    — byte totals in each arm's table match its static census, so the
    quantized arm shows up as a bytes (and bandwidth-demand) drop at
    equal step semantics."""
    import importlib.util
    import tempfile

    n = int(os.environ.get("DSTPU_MULTICHIP_DEVICES", "8"))
    # no XLA_FLAGS juggling here: pod_leg's _force_cpu_if_needed sets the
    # virtual device count before this child's first jax import
    spec = importlib.util.spec_from_file_location(
        "graft_entry", os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    "__graft_entry__.py"))
    graft = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(graft)
    t0 = time.perf_counter()

    def arm(tag, td, zero_config):
        from deepspeedsyclsupport_tpu.comm.topology import (
            reset_world_topology)

        reset_world_topology()
        report = graft.pod_leg(n, os.path.join(td, f"telemetry_{tag}"),
                               steps=6, emit_metrics_line=False,
                               zero_config=zero_config)
        dec = report["decomposition"]
        return {
            "n_steps": report["n_steps"],
            "ranks": len(report["ranks"]),
            "comm_bound_frac": round(dec["comm_bound_frac"] or 0.0, 4),
            "per_class_bandwidth_gbps": {
                cls: row["effective_gbps"]
                for cls, row in dec["classes"].items()},
            "class_bytes_per_step": {
                cls: row["bytes_per_step"]
                for cls, row in dec["classes"].items()},
            "exposed_comm_s": dec["exposed_comm_s"],
            "compute_floor_s": dec["compute_floor_s"],
            "census_bytes_match": report["census"]["bytes_match"],
            "skew_p95_s": report["skew"]["p95"],
        }

    def dense_arm(tag, td, zero_config):
        """One quantized-A/B arm on a DENSE model (no internal sharding
        constraints): the ZeRO++ shard_map step rejects the transformer's
        in-graph constraints on this jax version (pre-existing zeropp
        limitation — its test suite runs dense models for the same
        reason), and the wire-byte proof is about the collectives, not
        the model. Both arms run THIS model, so the ratio is apples to
        apples."""
        import jax
        import numpy as np

        import deepspeedsyclsupport_tpu as ds
        from deepspeedsyclsupport_tpu.comm.topology import (
            reset_world_topology)
        from deepspeedsyclsupport_tpu.monitor import pod as pod_lib

        reset_world_topology()
        devs = jax.devices()[:n]
        fsdp = 2 if n % 2 == 0 else 1
        topo = ds.build_topology(devices=devs, dp=n // fsdp, fsdp=fsdp)

        class RectModel:
            def init_params(self):
                rng = np.random.default_rng(0)
                return {"w": rng.normal(0, 0.1, (256, 2048))
                        .astype(np.float32),
                        "b": np.zeros((2048,), np.float32)}

            def loss(self, params, batch, rng):
                import jax.numpy as jnp

                y = jnp.tanh(batch["x"] @ params["w"] + params["b"])
                return jnp.mean((y - batch["y"]) ** 2)

        tdir = os.path.join(td, f"telemetry_{tag}")
        dp_ws = max(topo.get_data_parallel_world_size(), 1)
        config = {
            "train_batch_size": 2 * dp_ws,
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
            "zero_optimization": dict(zero_config),
            "steps_per_print": 10_000,
            "comms_logger": {"enabled": True},
            "telemetry": {"enabled": True, "output_dir": tdir,
                          "heartbeat": {"enabled": False},
                          "memory_interval_steps": 0},
        }
        engine, _, _, _ = ds.initialize(model=RectModel(), config=config,
                                        topology=topo)
        rng = np.random.default_rng(1)
        bs = engine.train_batch_size()
        batch = {k: jax.device_put(v, engine.topology.data_sharding(v.ndim))
                 for k, v in
                 {"x": rng.normal(0, 1, (bs, 256)).astype(np.float32),
                  "y": rng.normal(0, 1, (bs, 2048)).astype(np.float32)
                  }.items()}
        for _ in range(6):
            engine.train_batch(batch)
        engine.emit_comm_census()
        engine.telemetry.close(f"multichip_{tag}")
        report = pod_lib.pod_report_from_paths([tdir])
        d = report.to_dict()
        dec = d["decomposition"]
        return {
            "comm_bound_frac": round(dec["comm_bound_frac"] or 0.0, 4),
            "class_bytes_per_step": {
                cls: row["bytes_per_step"]
                for cls, row in dec["classes"].items()},
            "per_class_bandwidth_gbps": {
                cls: row["effective_gbps"]
                for cls, row in dec["classes"].items()},
            "census_bytes_match": d["census"]["bytes_match"],
        }

    import jax

    with tempfile.TemporaryDirectory(prefix="dstpu_bench_pod_") as td:
        fp = arm("fp", td, {"stage": 3})
        _emit({"metric": "multichip_comm_bound_frac_fp", "value":
               fp["comm_bound_frac"], "unit": "frac", "vs_baseline": None,
               "detail": {"platform": jax.devices()[0].platform,
                          "partial": True, **fp}})
        # quantized A/B: identical dense model/batch/steps per arm, so any
        # bytes delta is the TRANSPORT (qwZ int8 weight all-gather + qgZ
        # int8 grad all-to-all quant-reduce), not the workload
        try:
            ab = {"fp": dense_arm("dense_fp", td, {"stage": 3}),
                  "quantized": dense_arm(
                      "dense_q", td,
                      {"stage": 3, "zero_quantized_weights": True,
                       "zero_quantized_gradients": True})}
        except Exception as e:  # the A/B detail must never eat the rung
            ab = {"error": str(e)[:300]}
    detail = {
        "platform": jax.devices()[0].platform,
        "n_devices": n,
        **fp,
        "quantized_ab": ab,
        "wall_s": round(time.perf_counter() - t0, 1),
    }
    if "error" not in ab:
        # the wire-byte proof: per-class quantized/full-precision byte
        # ratio (int8 payload + block scales vs fp32 on these arms) and
        # the comm-boundedness delta at equal step semantics
        ratios = {}
        for cls, fp_bytes in ab["fp"]["class_bytes_per_step"].items():
            q_bytes = ab["quantized"]["class_bytes_per_step"].get(cls)
            if q_bytes is not None and fp_bytes:
                ratios[cls] = round(q_bytes / fp_bytes, 4)
        detail["quantized_bytes_ratio_by_class"] = ratios
        detail["comm_bound_frac_delta"] = round(
            ab["quantized"]["comm_bound_frac"]
            - ab["fp"]["comm_bound_frac"], 4)
        detail["total_bytes_ratio"] = round(
            sum(ab["quantized"]["class_bytes_per_step"].values())
            / max(sum(ab["fp"]["class_bytes_per_step"].values()), 1e-9), 4)
    _emit({
        "metric": "multichip_comm_bound_frac",
        "value": fp["comm_bound_frac"],
        "unit": "frac", "vs_baseline": None,
        "detail": detail})


# ======================================================================
# rung: offload (beyond-HBM: bucketed D2H / host-Adam / H2D pipeline)
# ======================================================================
def run_offload():
    """In-HBM vs cpu vs nvme offload arms at a model whose fp32 training
    state (master + moments + grads, 16 B/param) exceeds a notional HBM
    budget — the ZeRO-Infinity story on the CPU sim. Headlines the
    step-time overhead ratio of offloading and the pipeline's overlap
    efficiency (1 − exposed/total transfer time,
    ``runtime/offload_pipeline.py``); the nvme arm additionally proves the
    bounded moment window (host-RAM high-water ≤ the configured bound)."""
    jax = _child_jax()
    import gc
    import tempfile

    import numpy as np

    import deepspeedsyclsupport_tpu as ds
    from deepspeedsyclsupport_tpu.comm.topology import reset_world_topology
    from deepspeedsyclsupport_tpu.models import build_model, get_config

    platform = jax.devices()[0].platform
    budget_mb = float(os.environ.get("DSTPU_OFFLOAD_HBM_BUDGET_MB", "48"))
    hidden = int(os.environ.get("DSTPU_OFFLOAD_HIDDEN", "288"))
    layers = int(os.environ.get("DSTPU_OFFLOAD_LAYERS", "3"))
    seq, micro_bs, steps, warm = 256, 4, 4, 1
    mcfg = get_config("tiny", hidden_size=hidden,
                      intermediate_size=3 * hidden, num_layers=layers,
                      num_heads=4, num_kv_heads=4, vocab_size=4096,
                      max_seq_len=seq)
    n_params = mcfg.param_count()
    state_bytes = 16 * n_params  # fp32 master + m + v + grads
    bucket = int(os.environ.get("DSTPU_OFFLOAD_BUCKET", 2 * 2 ** 20))

    def arm(tag, zero_cfg, telemetry_dir=None):
        reset_world_topology()
        topo = ds.build_topology(dp=1)
        model = build_model(mcfg)
        config = {
            "train_batch_size": micro_bs,
            "train_micro_batch_size_per_gpu": micro_bs,
            "optimizer": {"type": "adam", "params": {"lr": 1e-4}},
            "bf16": {"enabled": True},
            "zero_optimization": zero_cfg,
            "steps_per_print": 10_000,
        }
        if telemetry_dir is not None:
            # goodput evidence for the offload_stall bucket (accounting
            # must stay >= 99% with the new category in play)
            config["telemetry"] = {"enabled": True,
                                   "output_dir": telemetry_dir,
                                   "heartbeat": {"enabled": False}}
        engine, _, _, _ = ds.initialize(model=model, config=config,
                                        topology=topo)
        batch = {"input_ids": jax.random.randint(
            jax.random.PRNGKey(0), (micro_bs, seq), 0, mcfg.vocab_size)}
        for _ in range(warm):
            m = engine.train_batch(batch)
        _sync(m["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            m = engine.train_batch(batch)
        _sync(m["loss"])
        dt = (time.perf_counter() - t0) / steps
        out = {"step_s": round(dt, 4),
               "loss": round(float(np.asarray(m["loss"])), 4)}
        mh = engine._mh_offload
        if mh is not None:
            s = mh.offload_summary()
            out["offload"] = {
                k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in s.items()}
            out["overlap_efficiency"] = round(s["overlap_efficiency"], 4)
            if "window_bound_bytes" in s:  # nvme arm only
                out["window_bounded"] = bool(
                    s["window_hwm_bytes"] <= s["window_bound_bytes"])
        if engine.telemetry is not None and engine.telemetry.goodput:
            g = engine.telemetry.goodput.summary()
            known = sum(g.get(c, 0.0)
                        for c in engine.telemetry.goodput.CATEGORIES)
            out["goodput"] = {
                "accounted": round(known / g["total"], 4),
                "offload_stall_s": round(g.get("offload_stall", 0.0), 4)}
            engine.telemetry.close()
        del engine
        gc.collect()
        jax.clear_caches()
        return out

    with tempfile.TemporaryDirectory(prefix="dstpu_bench_offload_") as td:
        arms = {"hbm": arm("hbm", {"stage": 0})}
        arms["cpu"] = arm("cpu", {
            "stage": 2,
            "offload_optimizer": {"device": "cpu", "bucket_size": bucket}},
            telemetry_dir=os.path.join(td, "telemetry"))
        arms["nvme"] = arm("nvme", {
            "stage": 2,
            "offload_optimizer": {"device": "nvme", "bucket_size": bucket,
                                  "buffer_count": 2,
                                  "nvme_path": os.path.join(td, "swap")}})
    for tag, a in arms.items():
        _emit({"metric": f"offload_step_s_{tag}", "value": a["step_s"],
               "unit": "s", "vs_baseline": None,
               "detail": {"platform": platform, "partial": True, **a}})
    ratio = round(arms["cpu"]["step_s"] / max(arms["hbm"]["step_s"], 1e-9), 3)
    _emit({
        "metric": "offload_overhead_ratio",
        "value": ratio,
        "unit": "x", "vs_baseline": None,
        "detail": {
            "platform": platform,
            "baseline": "offloaded (cpu arm) vs in-HBM step time; "
                        "ZeRO-Infinity's bar is overhead hidden behind "
                        "overlap, bounded-memory tiers",
            "n_params": n_params,
            "state_mb": round(state_bytes / 2**20, 1),
            "hbm_budget_mb": budget_mb,
            "exceeds_budget": bool(state_bytes > budget_mb * 2**20),
            "bucket_bytes": bucket,
            "nvme_overhead_ratio": round(
                arms["nvme"]["step_s"] / max(arms["hbm"]["step_s"], 1e-9),
                3),
            "overlap_efficiency_cpu": arms["cpu"].get("overlap_efficiency"),
            "overlap_efficiency_nvme": arms["nvme"].get(
                "overlap_efficiency"),
            "meets_overlap_floor": bool(
                (arms["cpu"].get("overlap_efficiency") or 0.0) >= 0.5),
            "window_bounded": arms["nvme"].get("window_bounded"),
            "goodput": arms["cpu"].get("goodput"),
            "arms": arms,
        }})


# ======================================================================
# rung: serve (FastGen-style TTFT / throughput, SplitFuse A-B)
# ======================================================================
def _request_waterfall(session_traces, router_records=()):
    """Per-load-point request-time attribution (``detail.request_waterfall``):
    join the in-memory trace rings drained from the point's sessions (plus
    the router's, in the fleet rung) through ``monitor.reqtrace`` and
    compact the payload for a bench line — ``bench_diff`` gates the
    per-stage TTFT p95s in it."""
    from deepspeedsyclsupport_tpu.monitor import reqtrace

    try:
        att = reqtrace.waterfall(
            [(rid, "", list(recs)) for rid, recs in session_traces],
            router_records=list(router_records))
    except Exception as e:  # attribution is a detail, never the rung
        return {"error": str(e)[:200]}
    att["slo_burn"].pop("windows", None)  # per-window rows are report fuel
    att["worst"] = att["worst"][:3]
    return att


def _drive_serving(eng, prompts, n_clients, reqs_per_client, gen_len, mode,
                   uid_base, arrival_of=None, deadline=None):
    """Closed-loop clients over the v2 engine at single-forward granularity.

    mode="splitfuse": decode tokens and (chunked) prompt tokens fuse into
    the same forward — the SplitFuse schedule. mode="naive": a waiting
    prompt preempts decoding and prefills to completion first (the
    static-batching behavior the FastGen blog A-Bs against,
    ``blogs/deepspeed-fastgen/README.md:139``).

    ``arrival_of``: uid → seconds-after-start arrival offset. Staggered
    first arrivals create the steady-state mix the blog measures — prompts
    landing WHILE other clients decode (an all-at-t0 burst lets the naive
    arm batch every prefill upfront and never preempt a decode, which is
    not the scenario the SplitFuse claim is about). A request's TTFT clock
    starts at its arrival.

    ``deadline`` (``time.perf_counter()`` base): overrunning it raises
    :class:`_ScenarioTimeout` so the caller keeps earlier scenarios'
    results instead of losing the whole rung to one slow load point.
    """
    import jax.numpy as jnp

    arrival_of = arrival_of or {}

    ttfts, itls = [], []
    submitted, last_tok, gen_count = {}, {}, {}
    live, waiting = {}, []
    pending_tok = {}    # uid -> sampled decode token not yet admitted
    awaiting = set()    # uids with a forward in flight (fresh logits coming)
    ttft_done = set()
    ttft_of = {}        # uid -> measured TTFT (goodput-rung SLA input)
    next_req = [0] * n_clients
    finished = evicted = evicted_tokens = total_decoded = stall_guard = 0
    total = n_clients * reqs_per_client
    req_stats = []      # (submit_t, done_t, tokens, was_evicted) per request

    def submit(c, now):
        i = next_req[c]
        next_req[c] += 1
        uid = uid_base + c * 1000 + i
        waiting.append((uid, c))
        submitted[uid] = max(now, t0 + arrival_of.get(uid, 0.0))

    def arrived(uid, now):
        return submitted[uid] <= now

    def retire(uid, now, was_evicted=False):
        nonlocal finished
        c = live.pop(uid)
        eng.flush([uid])
        pending_tok.pop(uid, None)
        awaiting.discard(uid)
        finished += 1
        req_stats.append((submitted[uid], now, gen_count.get(uid, 0),
                          was_evicted, ttft_of.get(uid, 0.0)))
        if next_req[c] < reqs_per_client:
            submit(c, now)

    # pre-warm the device argmax/max executables OUTSIDE the timed window
    # (they are new eager dispatches per logits shape; their first-call
    # compile must not land in the naive arm's first TTFT/ITL samples)
    warm = eng.put([uid_base - 1], [[1, 2, 3]])[uid_base - 1]
    float(jnp.max(warm))
    int(jnp.argmax(warm))
    eng.flush([uid_base - 1])
    # snapshot AFTER the warmup so its dispatches stay out of the metrics
    dispatches0 = getattr(eng, "host_dispatches", 0)

    t0 = time.perf_counter()
    for c in range(n_clients):
        submit(c, t0)
    while finished < total:
        now = time.perf_counter()
        if deadline is not None and now > deadline:
            raise _ScenarioTimeout(
                f"{mode}: scenario deadline after {finished}/{total} "
                f"requests ({total_decoded} tokens)")
        # prompts first in naive mode: they preempt and fully prefill
        if mode == "naive" and waiting:
            admit_u, admit_t = [], []
            while waiting:
                uid, c = waiting[0]
                if not arrived(uid, now):
                    break
                res = eng.check_schedule(admit_u + [uid],
                                         [len(t) for t in admit_t]
                                         + [len(prompts[uid])])
                if uid in res.rejected:
                    break
                waiting.pop(0)
                admit_u.append(uid)
                admit_t.append(prompts[uid])
                live[uid] = c
            if admit_u:
                eng.put(admit_u, admit_t, drain=True)  # decode stalls
                # logits are device-resident and put() is async-dispatch:
                # force completion BEFORE stamping TTFT (scalar fetch — a
                # full-logits pull would add V*4B per seq of tunnel
                # traffic to the timed path)
                for uid in admit_u:
                    lg = eng.query(uid)
                    if lg is not None:
                        float(jnp.max(lg))
                now = time.perf_counter()
                for uid in admit_u:
                    ttfts.append(now - submitted[uid])
                    ttft_of[uid] = now - submitted[uid]
                    ttft_done.add(uid)
                    last_tok[uid] = now
                    gen_count[uid] = 0
                    awaiting.add(uid)
                stall_guard = 0
                continue
        # consume fresh logits: sample one token per drained live sequence
        for uid in list(live):
            if uid not in awaiting:
                continue
            lg = eng.query(uid)
            if lg is None:
                continue
            awaiting.discard(uid)
            # device-side argmax: the sampled token (one scalar) is all
            # that crosses to the host — matching real serving, where the
            # sampler lives on device; the int() fetch is the barrier that
            # makes the timestamp honest
            tok = int(jnp.argmax(lg))
            now = time.perf_counter()
            if uid not in ttft_done:      # prompt just drained (splitfuse)
                ttfts.append(now - submitted[uid])
                ttft_of[uid] = now - submitted[uid]
                ttft_done.add(uid)
            else:
                itls.append(now - last_tok[uid])
            last_tok[uid] = now
            gen_count[uid] += 1
            total_decoded += 1
            if gen_count[uid] >= gen_len:
                retire(uid, now)
            else:
                pending_tok[uid] = tok
        put_uids = list(pending_tok)
        put_toks = [[pending_tok[u]] for u in put_uids]
        if mode == "splitfuse":
            while waiting:
                uid, c = waiting[0]
                if not arrived(uid, now):
                    break
                res = eng.check_schedule(put_uids + [uid],
                                         [len(t) for t in put_toks]
                                         + [len(prompts[uid])])
                if uid in res.rejected:
                    break
                waiting.pop(0)
                put_uids.append(uid)
                put_toks.append(prompts[uid])
                live[uid] = c
                gen_count[uid] = 0
        in_flight = any(d.pending for d in eng.seqs.values())
        if not put_uids and not in_flight:
            # quiet because the next request hasn't ARRIVED yet (staggered
            # load): idle-wait to its arrival — that is offered-load slack,
            # not a scheduler stall
            future = [submitted[u] for u, _ in waiting
                      if not arrived(u, now)]
            if future and not live:
                wake = min(future) if deadline is None \
                    else min(min(future), deadline)
                time.sleep(max(0.0, wake - time.perf_counter()))
                stall_guard = 0
                continue
            stall_guard += 1
            if stall_guard > 3:
                raise RuntimeError(
                    f"serving loop stalled: {len(waiting)} waiting, "
                    f"{len(live)} live, {finished}/{total} done")
            continue
        res = eng.put(put_uids, put_toks, drain=False)
        for uid in res.admission.admitted:
            if uid in pending_tok:
                del pending_tok[uid]
            awaiting.add(uid)
        # KV-pool pressure: a rejected decode token means its sequence can't
        # grow — evict the longest-context live sequence (truncation, like
        # generate()) so decode always progresses; tokens are only counted
        # when a forward actually ran for them
        if (pending_tok and not res.admission.admitted and not in_flight):
            victim = max(live, key=lambda u: eng.seqs[u].n_cached
                         if u in eng.seqs else -1)
            # an evicted request finished with < gen_len tokens: exclude its
            # tokens from the throughput numerator so the A-B arms compare
            # EQUAL work (finished requests x gen_len each) even if their
            # eviction rates differ
            evicted_tokens += gen_count.get(victim, 0)
            retire(victim, now, was_evicted=True)
            evicted += 1
        stall_guard = 0
    wall = time.perf_counter() - t0
    return _serving_result(wall, total, evicted, total_decoded,
                           evicted_tokens, ttfts, itls,
                           getattr(eng, "host_dispatches", 0) - dispatches0,
                           req_stats)


def _serving_result(wall, total, evicted, total_decoded, evicted_tokens,
                    ttfts, itls, dispatches, req_stats):
    """One result-dict schema for every serving arm — the A-B comparison
    depends on both arms computing percentiles/goodput identically."""
    ttfts = sorted(ttfts)
    itls = sorted(itls)

    def pct(xs, p):
        return xs[min(len(xs) - 1, int(p * len(xs)))] if xs else 0.0

    counted = total_decoded - evicted_tokens
    itl_mean = sum(itls) / len(itls) if itls else 0.0
    itl_var = (sum((x - itl_mean) ** 2 for x in itls) / len(itls)
               if itls else 0.0)
    return {"wall_s": round(wall, 3),
            "requests": total,
            "evicted": evicted,
            "tokens_generated": counted,
            "tokens_evicted": evicted_tokens,
            "throughput_tok_s": round(counted / max(wall, 1e-9), 2),
            "ttft_p50_s": round(pct(ttfts, 0.50), 4),
            "ttft_p95_s": round(pct(ttfts, 0.95), 4),
            "itl_p50_s": round(pct(itls, 0.50), 4),
            "itl_p95_s": round(pct(itls, 0.95), 4),
            "itl_std_s": round(itl_var ** 0.5, 5),
            "host_dispatches": dispatches,
            "host_dispatches_per_token": round(dispatches / max(counted, 1),
                                               3),
            "req_stats": req_stats}


def _drive_serving_sla(eng, prompts, n_clients, reqs_per_client, gen_len,
                       uid_base, arrival_of=None, deadline=None,
                       ttft_sla=None, rate_sla=None, capacity=None,
                       journal_dir=None, crash_at_tokens=None):
    """Closed-loop clients over the SLA serving policy layer
    (``inference/v2/serving.ServingSession``) — the third arm next to
    ``_drive_serving``'s naive/splitfuse: admission control (queue/shed),
    slack-ordered batch composition, lowest-slack KV preemption, and fused
    K-step decode whenever every live stream is in steady state.

    ``journal_dir`` + ``crash_at_tokens`` turn the drive into the
    AVAILABILITY arm: requests are journaled, and once ``crash_at_tokens``
    total tokens have been emitted the serving replica "dies" mid-decode —
    KV state, descriptors and all session policy state are dropped; a
    replacement session on the warm engine replays the journal from each
    stream's emitted-token watermark and the drive continues. The wall
    clock keeps running through the failover, so goodput-with-recovery
    honestly includes the recovery gap. (A warm replacement isolates the
    REPLAY cost; the cold-start path — process death, restart, compile —
    is the supervisor e2e's job, ``tests/unit/test_serving_resilience``.)

    Returns the same result dict as ``_drive_serving`` plus a ``serve``
    sub-dict (admitted/queued/shed/evicted counters and ``shed_pct``). A
    shed request enters ``req_stats`` with zero tokens and the evicted flag
    — an SLA miss — so goodput compares EQUAL offered load across arms;
    graceful degradation shows up as shed_pct rising while goodput stays
    above zero, instead of every stream missing together (r05 at 10
    clients). Token timestamps come from the session's event stream; a
    fused burst of k tokens lands at one instant and contributes k ITL
    samples of delta/k (the amortized steady-state rate — per-token
    intervals inside one device dispatch are not observable by design)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeedsyclsupport_tpu.inference.sampling import SamplingParams
    from deepspeedsyclsupport_tpu.inference.v2 import (ServingPolicyConfig,
                                                       ServingSession)

    from deepspeedsyclsupport_tpu.inference.v2.supervisor import journal_path

    arrival_of = arrival_of or {}
    have_sla = ttft_sla is not None or bool(rate_sla)
    pol = ServingPolicyConfig(
        admission="sla" if have_sla else "none",
        ttft_sla_s=ttft_sla, token_rate_sla=rate_sla or 0.0,
        shed_policy="queue", preempt_policy="reject",
        max_queue_s=(4.0 * ttft_sla if ttft_sla else 60.0),
        journal_path=(journal_path(journal_dir, attempt=0)
                      if journal_dir else None))
    # `capacity` is SHARED across the sweep's arms: the solo calibration
    # run measures real prefill/decode rates into it, so the admission gate
    # at every load point projects from measurements, not priors
    sess = ServingSession(eng, pol, capacity=capacity)
    crashed = False
    recovery_summary = None
    trace_records = []

    ttfts, itls = [], []
    submitted, last_tok, gen_count, ttft_of = {}, {}, {}, {}
    client_of = {}
    next_req = [0] * n_clients
    finished = evicted = shed = evicted_tokens = total_decoded = 0
    stall_guard = 0
    total = n_clients * reqs_per_client
    req_stats = []
    due = []  # (when, uid, client) arrivals not yet submitted

    # pre-warm the sampler executable OUTSIDE the timed window (first-call
    # compile must not land in the first TTFT/ITL samples)
    eng.put([uid_base - 1], [[1, 2, 3]])
    lg = eng.query(uid_base - 1)
    sp = SamplingParams()
    np.asarray(eng._sample_fn(jnp.stack([lg]), jax.random.PRNGKey(0),
                              jnp.float32(sp.temperature),
                              jnp.float32(sp.top_p), sp.structure))
    eng.flush([uid_base - 1])
    dispatches0 = getattr(eng, "host_dispatches", 0)

    t0 = time.perf_counter()

    def queue_next(c, when):
        i = next_req[c]
        next_req[c] += 1
        uid = uid_base + c * 1000 + i
        due.append((when, uid, c))
        client_of[uid] = c

    def record_done(uid, now, was_evicted):
        nonlocal finished
        finished += 1
        req_stats.append((submitted[uid], now, gen_count.get(uid, 0),
                          was_evicted, ttft_of.get(uid, 0.0)))
        c = client_of[uid]
        if next_req[c] < reqs_per_client:
            queue_next(c, now)  # closed loop: next request on completion

    for c in range(n_clients):
        queue_next(c, t0 + arrival_of.get(uid_base + c * 1000 + 0, 0.0))

    while finished < total:
        now = time.perf_counter()
        if deadline is not None and now > deadline:
            raise _ScenarioTimeout(
                f"sla: scenario deadline after {finished}/{total} requests "
                f"({total_decoded} tokens, {shed} shed)")
        for when, uid, c in [d for d in due if d[0] <= now]:
            due.remove((when, uid, c))
            submitted[uid] = max(now, when)
            gen_count[uid] = 0
            if sess.submit(uid, prompts[uid], gen_len, now=now) == "shed":
                shed += 1
                record_done(uid, now, was_evicted=True)
        events = sess.step()
        for ev in events:
            if ev.kind == "token":
                uid = ev.uid
                n = len(ev.tokens)
                if uid not in ttft_of:
                    ttft_of[uid] = ev.t - submitted[uid]
                    ttfts.append(ttft_of[uid])
                    # tokens after the first in the SAME burst ride the
                    # prefill drain: no ITL samples for them
                else:
                    itl = (ev.t - last_tok[uid]) / n
                    itls.extend([itl] * n)
                last_tok[uid] = ev.t
                gen_count[uid] += n
                total_decoded += n
            elif ev.kind == "finish":
                was_evicted = ev.reason == "evicted"
                if was_evicted:
                    evicted += 1
                    evicted_tokens += gen_count.get(ev.uid, 0)
                record_done(ev.uid, ev.t, was_evicted)
            elif ev.kind == "shed":
                shed += 1
                record_done(ev.uid, ev.t, was_evicted=True)
        if (crash_at_tokens is not None and not crashed
                and total_decoded >= crash_at_tokens):
            # ------- injected replica death + journal-replay failover
            import dataclasses as _dc

            from deepspeedsyclsupport_tpu.inference.v2 import (
                load_journal, recover_requests)

            crashed = True
            eng.flush(list(eng.seqs))   # KV state + descriptors lost
            # the dead incarnation's trace ring survives the crash (it is
            # host memory, like the journal is disk) — bank it for the
            # point's request waterfall before the session goes away
            trace_records.extend(sess.drain_trace())
            sess.close()
            states, last_t = load_journal(journal_dir)
            sess = ServingSession(
                eng, _dc.replace(pol, journal_path=journal_path(
                    journal_dir, attempt=1)),
                capacity=capacity)
            recovery_summary = recover_requests(sess, states, last_t)
            now = time.perf_counter()
            for uid in recovery_summary["shed"]:
                # a replay shed is terminal without a session event —
                # account it as an SLA miss like any other shed
                shed += 1
                record_done(uid, now, was_evicted=True)
            continue
        if events:
            stall_guard = 0
            continue
        if sess.idle and due:
            wake = min(w for w, _u, _c in due)
            if deadline is not None:
                wake = min(wake, deadline)
            time.sleep(max(0.0, wake - time.perf_counter()))
            stall_guard = 0
            continue
        stall_guard += 1
        if stall_guard > 200:
            raise RuntimeError(
                f"sla serving loop stalled: {sess.stats()}, "
                f"{finished}/{total} done")
    wall = time.perf_counter() - t0
    res = _serving_result(wall, total, evicted, total_decoded,
                          evicted_tokens, ttfts, itls,
                          getattr(eng, "host_dispatches", 0) - dispatches0,
                          req_stats)
    st = sess.stats()
    res["serve"] = {"admitted": st["admitted"], "queued": st["queued"],
                    "shed": shed, "evicted": st["evicted"],
                    "shed_pct": round(100.0 * shed / max(total, 1), 1),
                    "prefill_tok_s_est": st["prefill_tok_s_est"],
                    "decode_step_s_est": st["decode_step_s_est"]}
    if recovery_summary is not None:
        res["serve"]["recovery"] = {
            "replays": len(recovery_summary["replayed"]),
            "replay_sheds": len(recovery_summary["shed"]),
            "time_to_recover_s": recovery_summary["time_to_recover_s"]}
    trace_records.extend(sess.drain_trace())
    res["trace"] = trace_records
    if journal_dir is not None:
        sess.close()
    return res


def _serve_once(model_name, platform, *, n_clients, reqs_per_client,
                prompt_len, gen_len, budget, block_size, max_context,
                attn=None, scenario_budget_s=None):
    import jax

    from deepspeedsyclsupport_tpu.inference.v2 import InferenceEngineV2
    from deepspeedsyclsupport_tpu.models import build_model, get_config

    cfg = get_config(model_name, max_seq_len=max_context)
    model = build_model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    max_seqs = max(8, 2 * n_clients)
    extra = _attn_overrides(attn)
    eng = InferenceEngineV2(model, params,
                            config={"max_tokens_per_batch": budget,
                                    "block_size": block_size,
                                    "max_context": max_context,
                                    "max_sequences": max_seqs,
                                    # fully-committed KV pool: a decode
                                    # token can never be rejected, so the
                                    # driver's eviction path stays cold
                                    "num_blocks": max_seqs
                                    * (max_context // block_size),
                                    **extra})
    import numpy as np

    rng = np.random.RandomState(0)

    def mk_prompt():
        return [int(t) for t in rng.randint(1, cfg.vocab_size - 1,
                                            size=prompt_len)]

    # compile prefill + decode in both KV-sharding states outside the
    # timed window (engine-owned warmup; see InferenceEngineV2.warmup)
    eng.warmup()

    results = {}
    for i, mode in enumerate(("naive", "splitfuse")):
        uid_base = (i + 1) * 1_000_000
        prompts = {}
        for c in range(n_clients):
            for r in range(reqs_per_client):
                prompts[uid_base + c * 1000 + r] = mk_prompt()
        deadline = (time.perf_counter() + scenario_budget_s
                    if scenario_budget_s else None)
        results[mode] = _drive_serving(eng, prompts, n_clients,
                                       reqs_per_client, gen_len, mode,
                                       uid_base, deadline=deadline)
        results[mode].pop("req_stats", None)  # per-request rows are
        # goodput-rung fuel, not serve-line payload
        # flush the completed arm NOW: if the other arm hangs/overruns,
        # the parent's partial-stdout parse still banks this measurement
        _emit({"metric": f"serve_arm_{mode}_{model_name}",
               "value": results[mode]["throughput_tok_s"],
               "unit": "tokens/s", "vs_baseline": 0.0,
               "detail": {"platform": platform, "partial": True,
                          "mode": mode, "clients": n_clients,
                          **results[mode]}})
    speedup = (results["splitfuse"]["throughput_tok_s"]
               / max(results["naive"]["throughput_tok_s"], 1e-9))
    sf = results["splitfuse"]
    return {
        "metric": f"serve_decode_tok_per_sec_per_chip_{model_name}",
        "value": sf["throughput_tok_s"],
        "unit": "tokens/s",
        "vs_baseline": round(speedup / REFERENCE_FASTGEN_SPEEDUP, 4),
        "detail": {"platform": platform, "model": model_name,
                   "clients": n_clients, "prompt_len": prompt_len,
                   "gen_len": gen_len, "token_budget": budget,
                   "attn_impl": attn or "auto",
                   "ttft_p50_s": sf["ttft_p50_s"],
                   "ttft_p95_s": sf["ttft_p95_s"],
                   "itl_p95_s": sf["itl_p95_s"],
                   "splitfuse_vs_naive_speedup": round(speedup, 3),
                   "naive": results["naive"], "splitfuse": sf,
                   "baseline": "SplitFuse-vs-naive effective-throughput "
                               "ratio vs the reference FastGen 2.3x "
                               "headline"},
    }


# ==================================================================
# rung: serve_goodput (the reference's ACTUAL headline metric — goodput
# under a per-client token-rate SLA across a load sweep;
# blogs/deepspeed-fastgen/README.md:28,139-177)
# ==================================================================
def _goodput(req_stats, sla_rate, ttft_sla, wall):
    """FastGen-style two-part SLA per request: first token within
    ``ttft_sla`` AND decode rate (tokens per second after the first token,
    queue time excluded) at least ``sla_rate``. Returns
    (goodput tokens/s, sla_miss_fraction)."""
    met_tokens = 0
    missed = 0
    for t_sub, t_done, toks, was_evicted, ttft in req_stats:
        decode_dur = max(t_done - t_sub - ttft, 1e-9)
        rate_ok = toks > 1 and (toks - 1) / decode_dur >= sla_rate
        if (not was_evicted) and ttft <= ttft_sla and rate_ok:
            met_tokens += toks
        else:
            missed += 1
    n = max(len(req_stats), 1)
    return met_tokens / max(wall, 1e-9), missed / n


def _serve_goodput_once(model_name, platform, *, client_sweep,
                        reqs_per_client, prompt_len, gen_len, budget,
                        block_size, max_context, attn=None,
                        sweep_budget_s=None):
    """Load sweep: closed-loop clients at increasing counts; SLA is a
    per-client token rate calibrated to 50% of the solo (1-client) decode
    rate — the blog's 'effective throughput under a latency SLA' shape.
    SplitFuse and naive run the SAME work at each load point.

    Per-scenario timeout (the r05 rc=124 fix): each completed load point is
    flushed as a partial JSON line the moment it finishes, every arm runs
    under a deadline carved from ``sweep_budget_s``, and a timed-out arm
    ends the sweep with the completed points reported — a sweep that dies
    at 10 clients still banks the 4- and 6-client measurements."""
    import jax
    import numpy as np

    from deepspeedsyclsupport_tpu.inference.v2 import InferenceEngineV2
    from deepspeedsyclsupport_tpu.models import build_model, get_config

    cfg = get_config(model_name, max_seq_len=max_context)
    model = build_model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    # engine capacity is deliberately CAPPED below the heaviest sweep point:
    # beyond-capacity load points (25/50 clients on CPU, 64 on TPU) are
    # exactly the overload the admission gate must degrade gracefully under
    # — and the padded forwards' per-step cost stays constant across the
    # sweep, so light-load points are not taxed for the heavy ones
    max_seqs = max(8, 2 * min(max(client_sweep),
                              16 if platform == "tpu" else 10))
    extra = _attn_overrides(attn)
    eng = InferenceEngineV2(model, params,
                            config={"max_tokens_per_batch": budget,
                                    "block_size": block_size,
                                    "max_context": max_context,
                                    "max_sequences": max_seqs,
                                    "num_blocks": max_seqs
                                    * (max_context // block_size),
                                    # SLA arm levers: fused K-step decode at
                                    # the pre-seed K-sweep knee + slack-based
                                    # KV eviction. max_prefill_fraction stays
                                    # 1.0: on the CPU sim the fraction only
                                    # SPREADS a prompt's fixed compute across
                                    # more mixed forwards (same total decode
                                    # stall, more dispatches) — admission is
                                    # the overload valve, not chunk shrinking
                                    "decode_steps_per_dispatch": 16,
                                    "eviction_policy": "slack",
                                    **extra})
    rng = np.random.RandomState(0)

    def prompts_for(uid_base, n_clients, reqs=None):
        return {uid_base + c * 1000 + r:
                [int(t) for t in rng.randint(1, cfg.vocab_size - 1,
                                             size=prompt_len)]
                for c in range(n_clients)
                for r in range(reqs or reqs_per_client)}

    eng.warmup(fused_ladder=True)  # pre-compile every fused-K rung: a tail
    # absorbing < K steps mid-sweep must not pay a compile inside a timed arm
    # ONE deadline covers calibration + sweep: the budget bounds the whole
    # call, not each phase separately
    sweep_end = (time.perf_counter() + sweep_budget_s
                 if sweep_budget_s else None)
    # SLA calibration: solo client, PER-TOKEN splitfuse arm — median ITL
    # sets the unloaded decode rate (SLA demands half of it, queue
    # excluded), solo TTFT sets the first-token bound (SLA allows 5x:
    # queueing headroom, the blog's latency-SLA shape). Per-token on
    # purpose, twice over: it keeps the SLA thresholds comparable with the
    # r05 baseline, and the fused-amortized solo ITL is 2-3x faster than
    # any sustainable loaded step time — calibrating off it would demand a
    # rate even graceful shedding cannot meet
    solo = _drive_serving(eng, prompts_for(9_000_000, 1), 1, 1,
                          gen_len, "splitfuse", 9_000_000,
                          deadline=sweep_end)
    solo.pop("req_stats", None)
    # seed the sweep-shared capacity model from the solo measurements so
    # the first load point's admission gate projects from data, not priors
    from deepspeedsyclsupport_tpu.inference.v2 import CapacityModel
    capacity = CapacityModel()
    capacity.record_prefill(prompt_len, max(solo["ttft_p50_s"], 1e-6))
    capacity.record_decode(1, max(solo["itl_p50_s"], 1e-6))
    solo_rate = 1.0 / max(solo["itl_p50_s"], 1e-6)
    sla_rate = 0.5 * solo_rate
    # TTFT bound stays loose (5x solo): the discriminating bound is the
    # decode rate — naive's prefill-preemption stalls every live decode,
    # which is exactly the behavior the blog's consistency curves indict
    ttft_sla = 5.0 * max(solo["ttft_p50_s"], 1e-3)

    # staggered first arrivals: clients spread over one solo request span,
    # so prompts land WHILE earlier clients decode (the blog's steady-state
    # mix); later requests are closed-loop
    solo_span = solo["ttft_p50_s"] + gen_len * solo["itl_p50_s"]

    points = []
    skipped = []
    best = None
    for li, n_clients in enumerate(client_sweep):
        point = {"clients": n_clients, "sla_tok_s": round(sla_rate, 2),
                 "sla_ttft_s": round(ttft_sla, 3)}
        timed_out = None
        for mi, mode in enumerate(("naive", "splitfuse")):
            if sweep_end is not None and time.perf_counter() > sweep_end:
                timed_out = f"{mode}: sweep budget exhausted before start"
                break
            uid_base = (li * 2 + mi + 1) * 1_000_000
            arrivals = {uid_base + c * 1000 + 0: c * solo_span / n_clients
                        for c in range(n_clients)}
            try:
                if mode == "splitfuse":
                    # the SplitFuse arm runs the full SLA policy layer:
                    # admission (queue/shed vs the calibrated SLA), slack
                    # scheduling, preemption, fused decode
                    r = _drive_serving_sla(
                        eng, prompts_for(uid_base, n_clients), n_clients,
                        reqs_per_client, gen_len, uid_base,
                        arrival_of=arrivals, deadline=sweep_end,
                        ttft_sla=ttft_sla, rate_sla=sla_rate,
                        capacity=capacity)
                else:
                    r = _drive_serving(eng, prompts_for(uid_base, n_clients),
                                       n_clients, reqs_per_client, gen_len,
                                       mode, uid_base, arrival_of=arrivals,
                                       deadline=sweep_end)
            except _ScenarioTimeout as e:
                timed_out = str(e)
                break
            gp, miss = _goodput(r.pop("req_stats"), sla_rate, ttft_sla,
                                r["wall_s"])
            if mode == "splitfuse":
                # per-load-point request-time attribution off the SLA
                # arm's in-memory trace ring (no disk IO in the timed path)
                point["request_waterfall"] = _request_waterfall(
                    [("0", r.pop("trace", []))])
            point[mode] = {"goodput_tok_s": round(gp, 2),
                           "sla_miss_pct": round(100 * miss, 1),
                           "shed_pct": r.get("serve", {}).get("shed_pct",
                                                              0.0),
                           "throughput_tok_s": r["throughput_tok_s"],
                           "ttft_p50_s": r["ttft_p50_s"],
                           "ttft_p95_s": r["ttft_p95_s"],
                           "itl_p50_s": r["itl_p50_s"],
                           "itl_p95_s": r["itl_p95_s"],
                           "itl_std_s": r["itl_std_s"],
                           "host_dispatches_per_token":
                               r["host_dispatches_per_token"],
                           **({"serve": r["serve"]} if "serve" in r else {})}
        if timed_out is not None:
            # the remaining (heavier) load points would also overrun:
            # stop the sweep, keep what completed
            skipped.append({"clients": n_clients, "reason": timed_out})
            skipped.extend({"clients": c, "reason": "after timeout"}
                           for c in client_sweep[li + 1:])
            print(f"serve_goodput: load point {n_clients} timed out "
                  f"({timed_out}); reporting {len(points)} completed "
                  f"point(s)", file=sys.stderr)
            break
        ratio = (point["splitfuse"]["goodput_tok_s"]
                 / max(point["naive"]["goodput_tok_s"], 1e-9))
        if point["naive"]["goodput_tok_s"] <= 0 and ratio > 100.0:
            # naive collapsed to zero goodput (the r05 overload signature):
            # any survivor makes the raw ratio unbounded — cap it so the
            # headline reads "graceful vs collapsed", not a fake 1e10x
            ratio = 100.0
            point["naive_collapsed"] = True
        point["goodput_ratio"] = round(ratio, 3)
        points.append(point)
        # flush the completed point NOW (partial line): a later kill —
        # SIGTERM, rc=124, a hung arm — cannot take it back
        _emit({"metric": f"serve_goodput_point_{model_name}",
               "value": point["splitfuse"]["goodput_tok_s"],
               "unit": "tokens/s", "vs_baseline": 0.0,
               "detail": {"platform": platform, "partial": True,
                          "point": point}})
        if best is None or ratio > best[1]:
            best = (n_clients, ratio, point)

    if best is None:
        raise RuntimeError(
            f"serve_goodput: no load point completed inside the sweep "
            f"budget ({sweep_budget_s}s); skipped={skipped}")

    # ------- availability detail: goodput THROUGH a fault. One extra run
    # of a completed load point with an injected mid-decode replica death
    # + journal-replay failover (inference/v2/supervisor.py), compared
    # against the SAME load's fault-free SLA arm from the sweep. The
    # pre-journal/pre-replay behavior was total loss of every in-flight
    # stream — the contract here is nonzero goodput through the fault.
    availability = None
    if points and (sweep_end is None
                   or sweep_end - time.perf_counter() > 60):
        import tempfile

        # lightest COMPLETED point (least fault-free shedding), with
        # enough requests per client that some are served entirely before
        # or after the fault — the streams live at the crash instant eat
        # the recovery gap in their decode rate (an honest SLA miss), so
        # the surviving goodput comes from the rest
        n_av = points[0]["clients"]
        av_reqs = max(3, reqs_per_client)
        uid_base = 17_000_000
        arrivals = {uid_base + c * 1000 + 0: c * solo_span / n_av
                    for c in range(n_av)}
        crash_tokens = max(8, n_av * av_reqs * gen_len // 4)
        try:
            with tempfile.TemporaryDirectory() as jdir:
                # fault-free arm at the SAME load shape (reqs differ from
                # the sweep point, so re-measure rather than reuse)
                ff_r = _drive_serving_sla(
                    eng, prompts_for(uid_base + 500, n_av, av_reqs),
                    n_av, av_reqs,
                    gen_len, uid_base + 500, arrival_of={
                        uid_base + 500 + c * 1000: c * solo_span / n_av
                        for c in range(n_av)},
                    deadline=sweep_end, ttft_sla=ttft_sla,
                    rate_sla=sla_rate, capacity=capacity)
                ff_gp, _ = _goodput(ff_r.pop("req_stats"), sla_rate,
                                    ttft_sla, ff_r["wall_s"])
                ff_r.pop("trace", None)
                r = _drive_serving_sla(
                    eng, prompts_for(uid_base, n_av, av_reqs),
                    n_av, av_reqs,
                    gen_len, uid_base,
                    arrival_of=arrivals, deadline=sweep_end,
                    ttft_sla=ttft_sla, rate_sla=sla_rate,
                    capacity=capacity, journal_dir=jdir,
                    crash_at_tokens=crash_tokens)
            gp, miss = _goodput(r.pop("req_stats"), sla_rate, ttft_sla,
                                r["wall_s"])
            availability = {
                "clients": n_av, "reqs_per_client": av_reqs,
                "crash_at_tokens": crash_tokens,
                # the trace spans BOTH incarnations: replay segments and
                # requeue waits show up as their own stages
                "request_waterfall": _request_waterfall(
                    [("0", r.pop("trace", []))]),
                "goodput_fault_free": round(ff_gp, 2),
                "goodput_with_recovery": round(gp, 2),
                "availability_ratio": round(gp / max(ff_gp, 1e-9), 3),
                "sla_miss_pct": round(100 * miss, 1),
                "recovery": r["serve"].get("recovery", {}),
                "baseline": "same-load fault-free SLA arm (availability "
                            "phase)"}
        except Exception as e:  # availability is a detail, never the rung
            availability = {"clients": n_av, "error": str(e)[:200]}

    return {
        "metric": f"serve_goodput_sla_{model_name}",
        "value": best[2]["splitfuse"]["goodput_tok_s"],
        "unit": "tokens/s",
        "vs_baseline": round(best[1] / REFERENCE_FASTGEN_SPEEDUP, 4),
        "detail": {"platform": platform, "model": model_name,
                   "prompt_len": prompt_len, "gen_len": gen_len,
                   "token_budget": budget,
                   "attn_impl": attn or "auto",
                   "sla": "per-request: TTFT <= 5x solo TTFT AND decode "
                          "rate (post-first-token) >= 50% of solo rate",
                   "best_load_point_clients": best[0],
                   "best_goodput_ratio_splitfuse_vs_naive": round(best[1], 3),
                   "load_sweep": points,
                   "load_points_skipped": skipped,
                   "availability": availability,
                   "baseline": "SplitFuse-vs-naive goodput ratio at the "
                               "best load point vs the reference FastGen "
                               "2.3x effective-throughput headline"},
    }


def run_serve_goodput():
    jax = _child_jax()

    platform = jax.devices()[0].platform
    if platform == "tpu":
        # sweeps extend past engine capacity (max_sequences caps at 2x16):
        # the 64-client point is pure overload — the admission gate's
        # graceful-shedding territory
        ladder = [
            dict(model_name="llama-650m", client_sweep=[4, 16, 32, 64],
                 reqs_per_client=2, prompt_len=512, gen_len=64, budget=256,
                 block_size=64, max_context=1024),
            # XLA fallback if the Pallas serving path trips remote Mosaic
            dict(model_name="llama-650m", client_sweep=[4, 16, 32, 64],
                 reqs_per_client=2, prompt_len=512, gen_len=64, budget=256,
                 block_size=64, max_context=1024, attn="xla"),
            dict(model_name="tiny", client_sweep=[4, 16, 32, 64],
                 reqs_per_client=2, prompt_len=512, gen_len=64, budget=256,
                 block_size=64, max_context=1024),
        ]
    else:
        # budget « prompt so chunking matters (VERDICT r4 #3), scaled to
        # what the CPU sim finishes inside the rung timeout; 25/50 clients
        # run 1.25x/2.5x past the engine's 20-slot capacity — the fleet-
        # scale overload points where shed_pct > 0 is the CORRECT outcome
        # NOTE on CPU-sim fidelity: a forward's wall time here scales
        # ~linearly with its token count, so a chunk-carrying fused forward
        # pays ~budget/decode-tokens more than a pure-decode forward — on
        # TPU at these sizes both are launch/HBM-bound and nearly equal,
        # which is the effect the SplitFuse headline rides. The CPU number
        # is therefore a structural UNDERestimate of the TPU ratio.
        ladder = [
            dict(model_name="tiny", client_sweep=[2, 6, 10, 25, 50],
                 reqs_per_client=1, prompt_len=512, gen_len=64, budget=96,
                 block_size=32, max_context=1024),
        ]
    # ONE budget for the whole rung, carved across ladder retries (see
    # run_serve); each config's sweep gets what the earlier ones left
    rung_end = time.monotonic() + float(
        os.environ.get("DSTPU_GOODPUT_SWEEP_BUDGET", 540))
    last_err = None
    for cfg in ladder:
        remaining = rung_end - time.monotonic()
        if remaining < 60:
            last_err = f"{cfg['model_name']}: skipped (rung budget)"
            break
        try:
            _emit(_serve_goodput_once(platform=platform,
                                      sweep_budget_s=remaining, **cfg))
            return
        except Exception as e:
            last_err = (f"{cfg['model_name']}[{cfg.get('attn') or 'auto'}]: "
                        f"{str(e)[:300]}")
            print(f"serve_goodput rung failed: {last_err}", file=sys.stderr)
            jax.clear_caches()
    raise RuntimeError(f"all serve_goodput rungs failed; last: {last_err}")


# ==================================================================
# rung: fleet (serving fleet control plane — routed goodput THROUGH a
# mid-sweep replica kill; inference/v2/fleet, docs/serving.md)
# ==================================================================
def _drive_fleet(router, replicas, prompts, n_clients, reqs_per_client,
                 gen_len, uid_base, arrival_of=None, deadline=None,
                 ttft_sla=None, rate_sla=None, kill_at_tokens=None,
                 kill_replica=None):
    """Closed-loop clients over the fleet router (in-process
    ``LocalReplica`` endpoints — the CPU-sim fleet). Same shape as
    ``_drive_serving_sla`` one level up: the router owns edge admission,
    placement and failover; this loop owns client pacing and delivery.

    ``kill_at_tokens`` + ``kill_replica`` inject the mid-sweep replica
    death: once that many tokens have been delivered fleet-wide, the
    replica dies hard (KV + session state dropped, journal left open) and
    the router's next poll claims its journaled in-flight streams and
    re-admits them on the survivors. The wall clock runs through the
    failover — goodput-through-fault includes the recovery gap honestly.

    Runs on wall clock (``time.time``): fleet observations join
    cross-process timestamps by contract, and the CPU-sim fleet keeps the
    same convention so the numbers compare."""
    from deepspeedsyclsupport_tpu.inference.v2.fleet import FleetRequest

    arrival_of = arrival_of or {}
    killed = kill_at_tokens is None
    total = n_clients * reqs_per_client
    submitted, gen_count, ttft_of, last_tok, client_of = {}, {}, {}, {}, {}
    next_req = [0] * n_clients
    finished = shed = evicted = evicted_tokens = total_decoded = 0
    req_stats = []
    due = []
    ttfts, itls = [], []
    failover_info = None
    # per-POINT breakdown: the router's ledgers are cumulative across the
    # sweep (one fleet, many load points) — delta them
    pr0 = {rid: dict(c) for rid, c in router.per_replica.items()}
    t0 = time.time()

    def queue_next(c, when):
        i = next_req[c]
        next_req[c] += 1
        uid = uid_base + c * 1000 + i
        due.append((when, uid, c))
        client_of[uid] = c

    def record_done(uid, now, was_evicted):
        nonlocal finished
        finished += 1
        req_stats.append((submitted[uid], now, gen_count.get(uid, 0),
                          was_evicted, ttft_of.get(uid, 0.0)))
        c = client_of[uid]
        if next_req[c] < reqs_per_client:
            queue_next(c, now)

    for c in range(n_clients):
        queue_next(c, t0 + arrival_of.get(uid_base + c * 1000 + 0, 0.0))

    stall_guard = 0
    while finished < total:
        now = time.time()
        if deadline is not None and now > deadline:
            raise _ScenarioTimeout(
                f"fleet: scenario deadline after {finished}/{total} "
                f"requests ({total_decoded} tokens, {shed} shed)")
        for when, uid, c in [d for d in due if d[0] <= now]:
            due.remove((when, uid, c))
            submitted[uid] = max(now, when)
            gen_count[uid] = 0
            outcome, _rid = router.submit(FleetRequest(
                uid=uid, tokens=prompts[uid], max_new_tokens=gen_len,
                tenant=f"client{c % 8}", ttft_sla_s=ttft_sla,
                rate_sla=rate_sla or 0.0), now=now)
            if outcome == "shed":
                shed += 1
                record_done(uid, now, was_evicted=True)
        events = router.poll(now=now)
        for ev in events:
            if ev.kind == "token":
                uid = ev.uid
                n = len(ev.tokens)
                if uid not in ttft_of:
                    ttft_of[uid] = ev.t - submitted[uid]
                    ttfts.append(ttft_of[uid])
                else:
                    itls.extend([(ev.t - last_tok[uid]) / n] * n)
                last_tok[uid] = ev.t
                gen_count[uid] += n
                total_decoded += n
            elif ev.kind == "finish":
                was_evicted = ev.reason == "evicted"
                if was_evicted:
                    evicted += 1
                    evicted_tokens += gen_count.get(ev.uid, 0)
                record_done(ev.uid, ev.t, was_evicted)
            elif ev.kind == "shed":
                shed += 1
                record_done(ev.uid, ev.t, was_evicted=True)
        if not killed and total_decoded >= kill_at_tokens:
            killed = True
            replicas[kill_replica].kill()
            # the NEXT poll observes the death and fails over (its events
            # flow through the normal delivery path above)
            failover_info = {
                "killed_replica": kill_replica,
                "at_tokens": total_decoded,
                "counters_before": dict(router.failover_counters)}
            continue
        if events:
            stall_guard = 0
            continue
        if router.idle and due:
            wake = min(w for w, _u, _c in due)
            if deadline is not None:
                wake = min(wake, deadline)
            time.sleep(max(0.0, wake - time.time()))
            stall_guard = 0
            continue
        stall_guard += 1
        if stall_guard > 500:
            raise RuntimeError(
                f"fleet loop stalled: {router.stats()}, "
                f"{finished}/{total} done")
    wall = time.time() - t0
    res = _serving_result(wall, total, evicted, total_decoded,
                          evicted_tokens, ttfts, itls, 0, req_stats)
    res.pop("host_dispatches", None)
    res.pop("host_dispatches_per_token", None)
    res["fleet"] = router.stats()
    res["fleet"]["point_shed"] = shed
    res["fleet"]["point_per_replica"] = {
        rid: {k: c[k] - pr0[rid].get(k, 0) for k in c}
        for rid, c in router.per_replica.items()}
    if failover_info is not None:
        before = failover_info.pop("counters_before")
        failover_info.update(
            {k: v - before.get(k, 0)
             for k, v in router.failover_counters.items()})
        res["fleet"]["failover"] = failover_info
    return res


def run_fleet():
    """2–4 replica CPU-sim fleet under 100+ concurrent clients with a
    mid-sweep replica kill: the headline is fleet goodput THROUGH the
    fault — nonzero, shed-accounted degradation instead of collapse.
    Every completed load point flushes as a partial JSON line (the same
    salvage contract as the serving sweeps) so an outer timeout still
    measures completed points."""
    jax = _child_jax()
    import shutil
    import tempfile

    import jax.numpy as jnp
    import numpy as np

    from deepspeedsyclsupport_tpu.inference.v2 import (InferenceEngineV2,
                                                       ServingPolicyConfig,
                                                       ServingSession)
    from deepspeedsyclsupport_tpu.inference.v2.fleet import (FleetConfig,
                                                             FleetRouter,
                                                             LocalReplica)
    from deepspeedsyclsupport_tpu.inference.v2.supervisor import journal_path
    from deepspeedsyclsupport_tpu.models import build_model, get_config

    platform = jax.devices()[0].platform
    n_replicas = int(os.environ.get("DSTPU_FLEET_REPLICAS", "3"))
    prompt_len, gen_len, reqs_per_client = 48, 16, 2
    max_seqs = 16
    # 12 = light (fleet capacity is 3x16 slots), 48 = at capacity,
    # 120 = pure overload — the edge gate's graceful-shedding territory
    client_sweep = [12, 48, 120]
    sweep_budget_s = float(os.environ.get("DSTPU_FLEET_SWEEP_BUDGET", 420))
    cfg = get_config("tiny", max_seq_len=256)
    model = build_model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)

    # shared per-tenant prompt HEADS (2 full blocks): clients of one
    # tenant open with the same system text — the workload shape the
    # tenant-affinity router co-locates and the replicas' prefix caches
    # convert into skipped prefill (stats()["realized_reuse"] is the join)
    head_len = 32
    tenant_heads = {g: [int(t) for t in rng.randint(
        1, cfg.vocab_size - 1, size=head_len)] for g in range(8)}

    def prompts_for(uid_base, n_clients):
        return {uid_base + c * 1000 + r:
                tenant_heads[c % 8]
                + [int(t) for t in rng.randint(1, cfg.vocab_size - 1,
                                               size=prompt_len - head_len)]
                for c in range(n_clients) for r in range(reqs_per_client)}

    root = tempfile.mkdtemp(prefix="dstpu_bench_fleet_")
    sessions = []

    def mk_engine():
        eng = InferenceEngineV2(
            model, params, dtype=jnp.bfloat16,
            config={"block_size": 16, "max_context": 256,
                    "max_tokens_per_batch": 96, "max_sequences": max_seqs,
                    "num_blocks": max_seqs * (256 // 16),
                    "decode_steps_per_dispatch": 8,
                    "eviction_policy": "slack"})
        eng.warmup(fused_ladder=True)
        return eng

    engines = [mk_engine() for _ in range(n_replicas)]
    deadline = time.time() + sweep_budget_s

    # SLA calibration: solo client, PER-TOKEN drive on one engine — the
    # fused-amortized solo ITL is far faster than any sustainable loaded
    # step time, so calibrating off it would demand a rate even graceful
    # shedding cannot meet (the serve_goodput calibration rule, verbatim)
    solo = _drive_serving(engines[0], prompts_for(9_000_000, 1), 1, 1,
                          gen_len, "splitfuse", 9_000_000)
    # looser factors than the single-replica serve_goodput SLA (5x TTFT /
    # 0.5x rate): on the CPU sim a mixed prefill+decode forward's wall
    # time scales with its token count, so a loaded fleet's per-stream
    # rate sits several x below the solo per-token rate by construction
    # (the serve_goodput NOTE on CPU-sim fidelity) — on TPU both are
    # launch/HBM-bound and the tighter factors would be the right call
    sla_rate = 0.25 / max(solo["itl_p50_s"], 1e-6)
    ttft_sla = 10.0 * max(solo["ttft_p50_s"], 1e-3)
    solo_span = solo["ttft_p50_s"] + gen_len * solo["itl_p50_s"]

    def mk_replica(rid):
        jdir = os.path.join(root, f"replica{rid}", "journal")
        os.makedirs(jdir, exist_ok=True)
        # replica sessions are structural-only (admission "none": queue on
        # engine limits) — SLA admission lives at the FLEET EDGE, in the
        # router, so hopeless requests shed before any replica queues
        sess = ServingSession(engines[int(rid)], ServingPolicyConfig(
            admission="none", journal_path=journal_path(jdir),
            prefix_cache={"enabled": True}))
        sessions.append(sess)
        return LocalReplica(str(rid), sess, journal_dir=jdir)

    replicas = {str(i): mk_replica(i) for i in range(n_replicas)}
    router = FleetRouter(
        list(replicas.values()),
        FleetConfig(affinity="tenant",
                    log_path=os.path.join(root, "router.jsonl")))
    # seed EVERY replica's router-side capacity model from the solo
    # measurements: the edge gate must project from data, not priors, for
    # replicas that have not served yet (the serve_goodput seeding rule)
    for cap in router.caps.values():
        cap.record_prefill(prompt_len, max(solo["ttft_p50_s"], 1e-6))
        cap.record_decode(1, max(solo["itl_p50_s"], 1e-6))
    points, skipped = [], []
    kill_done = False
    try:
        for li, n_clients in enumerate(client_sweep):
            if time.time() > deadline - 30:
                skipped.append({"clients": n_clients,
                                "reason": "sweep budget exhausted"})
                continue
            uid_base = (li + 1) * 1_000_000
            # paced arrivals: ~8 new clients per solo request span — a
            # sustained offered load, not one burst the first point's
            # still-calibrating capacity model cannot project
            arrivals = {uid_base + c * 1000 + 0: c * solo_span / 8.0
                        for c in range(n_clients)}
            # the mid-sweep kill lands in the HEAVIEST load point: fleet
            # goodput through the fault is the headline. The threshold is
            # sized to the fleet's live set (not offered load — overload
            # sheds most of that), so it fires mid-decode of the first
            # admitted wave.
            inject = (not kill_done and n_clients == max(client_sweep))
            try:
                r = _drive_fleet(
                    router, replicas, prompts_for(uid_base, n_clients),
                    n_clients, reqs_per_client, gen_len, uid_base,
                    arrival_of=arrivals, deadline=deadline,
                    ttft_sla=ttft_sla, rate_sla=sla_rate,
                    kill_at_tokens=(max_seqs * gen_len // 2 if inject
                                    else None),
                    kill_replica=("0" if inject else None))
            except _ScenarioTimeout as e:
                skipped.append({"clients": n_clients, "reason": str(e)})
                skipped.extend({"clients": c, "reason": "after timeout"}
                               for c in client_sweep[li + 1:])
                break
            if inject:
                kill_done = True
            gp, miss = _goodput(r.pop("req_stats"), sla_rate, ttft_sla,
                                r["wall_s"])
            fl = r["fleet"]
            point = {
                "clients": n_clients,
                "goodput_tok_s": round(gp, 2),
                "sla_miss_pct": round(100 * miss, 1),
                "shed_pct": round(100.0 * fl["point_shed"]
                                  / max(n_clients * reqs_per_client, 1), 1),
                "throughput_tok_s": r["throughput_tok_s"],
                "ttft_p50_s": r["ttft_p50_s"],
                "ttft_p95_s": r["ttft_p95_s"],
                "itl_p50_s": r["itl_p50_s"],
                "replicas_ready": fl["replicas_ready"],
                "replica_kill": fl.get("failover"),
                "per_replica": fl["point_per_replica"],
                # placement-side affinity joined with engine-reported
                # prefix reuse (cumulative across the sweep)
                "realized_reuse": {
                    k: v for k, v in (fl.get("realized_reuse") or {}).items()
                    if k != "per_replica"},
                # fleet-wide request waterfall for THIS point: every
                # replica's trace ring (the killed one's ring survives the
                # kill — host memory, like its journal survives on disk)
                # joined with the router's stream on one wall-clock base
                "request_waterfall": _request_waterfall(
                    [(rid, rep.session.drain_trace())
                     for rid, rep in replicas.items()],
                    router_records=router.drain_trace()),
            }
            points.append(point)
            # flush NOW: a later kill cannot take the completed point back
            _emit({"metric": "fleet_goodput_point_tiny",
                   "value": point["goodput_tok_s"], "unit": "tokens/s",
                   "vs_baseline": 0.0,
                   "detail": {"platform": platform, "partial": True,
                              "n_replicas": n_replicas, "point": point}})
    finally:
        router.close()
        for sess in sessions:
            try:
                sess.close()
            except Exception:
                pass
        shutil.rmtree(root, ignore_errors=True)
    if not points:
        raise RuntimeError(f"fleet: no load point completed; "
                           f"skipped={skipped}")
    fault_points = [p for p in points if p.get("replica_kill")]
    head = fault_points[-1] if fault_points else points[-1]
    _emit({
        "metric": "fleet_goodput_tiny",
        "value": head["goodput_tok_s"],
        "unit": "tokens/s",
        "vs_baseline": None,
        "detail": {
            "platform": platform, "model": "tiny",
            "n_replicas": n_replicas,
            "clients_at_headline": head["clients"],
            "sla": "per-request: TTFT <= 10x solo TTFT AND decode rate >= "
                   "25% of solo per-token rate (looser than serve_goodput's "
                   "5x/50%: the CPU sim's mixed-forward cost scales with "
                   "token count, structurally depressing loaded rates)",
            "sla_tok_s": round(sla_rate, 2),
            "sla_ttft_s": round(ttft_sla, 3),
            "headline": "fleet goodput THROUGH a mid-sweep replica kill "
                        "(nonzero + shed-accounted degradation, no "
                        "collapse)",
            "goodput_through_fault_nonzero": bool(
                head["goodput_tok_s"] > 0),
            "realized_reuse": head.get("realized_reuse"),
            "load_sweep": points,
            "load_points_skipped": skipped,
        }})


# ==================================================================
# rung: serve_prefix (cross-request KV prefix cache A/B — shared system
# prompt served cache-on vs cache-off; inference/v2/prefix_cache.py,
# docs/serving.md "prefix reuse")
# ==================================================================
def _drive_prefix_arm(eng, prefix_cache, prompts, gen_len, deadline=None):
    """Submit every request up-front (the queue absorbs the overflow —
    queue wait is part of TTFT, which is exactly what cached prefill
    shortens), drive the session to idle, return per-uid outputs + TTFT.
    Greedy sampling: outputs are a pure function of the prompt, the
    byte-identity oracle between the arms."""
    from deepspeedsyclsupport_tpu.inference.v2 import (ServingPolicyConfig,
                                                       ServingSession)

    sess = ServingSession(eng, ServingPolicyConfig(
        admission="none", shed_policy="queue", preempt_policy="requeue",
        prefix_cache=prefix_cache))
    outs, ttft, submitted = {}, {}, {}
    t0 = time.perf_counter()
    for uid in sorted(prompts):
        submitted[uid] = time.perf_counter()
        sess.submit(uid, prompts[uid], gen_len)
    steps = 0
    while not sess.idle:
        if deadline is not None and time.perf_counter() > deadline:
            raise _ScenarioTimeout(
                f"serve_prefix: arm deadline after {len(outs)}/"
                f"{len(prompts)} streams started")
        for ev in sess.step():
            if ev.kind == "token":
                if ev.uid not in ttft:
                    ttft[ev.uid] = ev.t - submitted[ev.uid]
                outs.setdefault(ev.uid, []).extend(ev.tokens)
        steps += 1
        if steps > 50_000:
            raise RuntimeError(f"serve_prefix arm stalled: {sess.stats()}")
    return {"outs": outs, "ttft": ttft,
            "wall_s": time.perf_counter() - t0,
            "serve": sess.stats(), "prefix": sess.prefix_stats(),
            "trace": sess.drain_trace()}


def _serve_prefix_once(model_name, platform, *, load_sweep, system_len,
                       tail_len, gen_len, budget, block_size, max_context,
                       attn=None, sweep_budget_s=None):
    """Shared-system-prompt workload (every request = system prompt +
    unique tail, the RAG/agent shape) served twice per load point on ONE
    warm engine: cache-off then cache-on. The contract: byte-identical
    outputs, hit ratio > 0.5 once the first wave has committed the system
    blocks, and lower mean TTFT (the cached arm's prefill is a block-table
    copy + the novel tail)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeedsyclsupport_tpu.inference.v2 import InferenceEngineV2
    from deepspeedsyclsupport_tpu.models import build_model, get_config

    assert system_len % block_size == 0, "system prompt must be full blocks"
    cfg = get_config(model_name, max_seq_len=max_context)
    model = build_model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    max_seqs = 4 if platform != "tpu" else 8
    extra = _attn_overrides(attn)
    eng = InferenceEngineV2(
        model, params, dtype=jnp.float32,
        config={"max_tokens_per_batch": budget, "block_size": block_size,
                "max_context": max_context, "max_sequences": max_seqs,
                # fully-committed pool minus nothing: KV pressure on the
                # cache-on arm is absorbed by index reclaim, not eviction
                "num_blocks": max_seqs * (max_context // block_size),
                **extra})
    eng.warmup()
    rng = np.random.RandomState(0)
    system = [int(t) for t in rng.randint(1, cfg.vocab_size - 1,
                                          size=system_len)]
    sweep_end = (time.perf_counter() + sweep_budget_s
                 if sweep_budget_s else None)
    # untimed warm drive: the first serving rounds compile the sampler +
    # the chunked-prefill shapes — neither arm may pay that inside a
    # timed point (the first point's off arm would otherwise read 10x+
    # slower on pure compile time)
    _drive_prefix_arm(
        eng, None,
        {90_000_000 + i: system
         + [int(t) for t in rng.randint(1, cfg.vocab_size - 1,
                                        size=tail_len)] for i in range(2)},
        gen_len, deadline=sweep_end)
    points, skipped = [], []
    for li, n_req in enumerate(load_sweep):
        if sweep_end is not None and time.perf_counter() > sweep_end:
            skipped.append({"requests": n_req, "reason": "sweep budget"})
            continue
        tails = [[int(t) for t in rng.randint(1, cfg.vocab_size - 1,
                                              size=tail_len)]
                 for _ in range(n_req)]
        arms = {}
        try:
            for ai, arm in enumerate(("off", "on")):
                # cache-off ALWAYS runs with no cache installed (the A/B
                # must not ride a previous point's warm index)
                eng.uninstall_prefix_cache()
                uid_base = (li * 2 + ai + 1) * 1_000_000
                prompts = {uid_base + i: system + tails[i]
                           for i in range(n_req)}
                arms[arm] = _drive_prefix_arm(
                    eng, {"enabled": True} if arm == "on" else None,
                    prompts, gen_len, deadline=sweep_end)
        except _ScenarioTimeout as e:
            skipped.append({"requests": n_req, "reason": str(e)})
            skipped.extend({"requests": r, "reason": "after timeout"}
                           for r in load_sweep[li + 1:])
            break
        # byte identity by request INDEX (uids differ across arms)
        identical = all(
            arms["off"]["outs"].get((li * 2 + 1) * 1_000_000 + i)
            == arms["on"]["outs"].get((li * 2 + 2) * 1_000_000 + i)
            for i in range(n_req))
        tt_off = sorted(arms["off"]["ttft"].values())
        tt_on = sorted(arms["on"]["ttft"].values())
        mean = lambda xs: sum(xs) / max(len(xs), 1)  # noqa: E731
        ps = arms["on"]["prefix"] or {}
        point = {
            "requests": n_req,
            "byte_identical": identical,
            "ttft_mean_off_s": round(mean(tt_off), 4),
            "ttft_mean_on_s": round(mean(tt_on), 4),
            "ttft_p95_off_s": round(tt_off[int(0.95 * (len(tt_off) - 1))], 4)
            if tt_off else None,
            "ttft_p95_on_s": round(tt_on[int(0.95 * (len(tt_on) - 1))], 4)
            if tt_on else None,
            "ttft_speedup": round(mean(tt_off) / max(mean(tt_on), 1e-9), 3),
            "wall_off_s": round(arms["off"]["wall_s"], 3),
            "wall_on_s": round(arms["on"]["wall_s"], 3),
            "hit_ratio": ps.get("hit_ratio", 0.0),
            "tokens_saved": ps.get("tokens_saved", 0),
            "blocks_shared": ps.get("blocks_shared", 0),
            "cow_copies": ps.get("cow_copies", 0),
            "shed_off": arms["off"]["serve"].get("shed", 0),
            "shed_on": arms["on"]["serve"].get("shed", 0),
            # cached-arm attribution: the cached_prefix mean + prefill-stage
            # quantiles are where the TTFT speedup must show up
            "request_waterfall": _request_waterfall(
                [("on", arms["on"].pop("trace", []))]),
        }
        points.append(point)
        _emit({"metric": f"serve_prefix_point_{model_name}",
               "value": point["ttft_speedup"], "unit": "x",
               "vs_baseline": 0.0,
               "detail": {"platform": platform, "partial": True,
                          "point": point}})
    eng.uninstall_prefix_cache()
    if not points:
        raise RuntimeError(f"serve_prefix: no load point completed; "
                           f"skipped={skipped}")
    head = points[-1]  # highest completed load point
    return {
        "metric": f"serve_prefix_ttft_speedup_{model_name}",
        "value": head["ttft_speedup"],
        "unit": "x",
        "vs_baseline": head["ttft_speedup"],
        "detail": {
            "platform": platform, "model": model_name,
            "system_len": system_len, "tail_len": tail_len,
            "gen_len": gen_len, "block_size": block_size,
            "attn_impl": attn or "auto",
            "byte_identical": head["byte_identical"],
            "hit_ratio": head["hit_ratio"],
            "tokens_saved": head["tokens_saved"],
            "load_sweep": points,
            "load_points_skipped": skipped,
            "baseline": "same engine, same prompts, prefix cache off — "
                        "mean-TTFT ratio at the highest completed load "
                        "point (byte-identical outputs required)"},
    }


def run_serve_prefix():
    jax = _child_jax()

    platform = jax.devices()[0].platform
    # system prompt is deliberately SEVERAL budget chunks long: the off
    # arm's prefill takes multiple chunked forwards, the on arm's cached
    # hit skips straight to the tail — the TTFT gap is the saved chunks
    if platform == "tpu":
        ladder = [
            dict(model_name="llama-650m", load_sweep=[8, 16, 32],
                 system_len=512, tail_len=64, gen_len=16, budget=256,
                 block_size=64, max_context=1024),
            dict(model_name="llama-650m", load_sweep=[8, 16, 32],
                 system_len=512, tail_len=64, gen_len=16, budget=256,
                 block_size=64, max_context=1024, attn="xla"),
            dict(model_name="tiny", load_sweep=[8, 16, 32],
                 system_len=512, tail_len=64, gen_len=16, budget=256,
                 block_size=64, max_context=1024),
        ]
    else:
        ladder = [
            dict(model_name="tiny", load_sweep=[4, 8, 16],
                 system_len=256, tail_len=32, gen_len=4, budget=96,
                 block_size=16, max_context=384),
        ]
    rung_end = time.monotonic() + float(
        os.environ.get("DSTPU_PREFIX_SWEEP_BUDGET", 360))
    last_err = None
    for cfg in ladder:
        remaining = rung_end - time.monotonic()
        if remaining < 30:
            last_err = f"{cfg['model_name']}: skipped (rung budget)"
            break
        try:
            _emit(_serve_prefix_once(platform=platform,
                                     sweep_budget_s=remaining, **cfg))
            return
        except Exception as e:
            last_err = (f"{cfg['model_name']}[{cfg.get('attn') or 'auto'}]: "
                        f"{str(e)[:300]}")
            print(f"serve_prefix rung failed: {last_err}", file=sys.stderr)
            jax.clear_caches()
    raise RuntimeError(f"all serve_prefix rungs failed; last: {last_err}")


# ==================================================================
# rung: serve_fused (device-resident multi-step decode A-B: K fused decode
# steps per dispatch vs one host round trip per token — VERDICT r4 #1;
# reference amortization: the MII loop over ragged kernels,
# deepspeed/inference/v2/engine_v2.py:107)
# ==================================================================
def _serve_fused_once(model_name, platform, *, n_clients, prompt_len,
                      gen_len, block_size, max_context, fused_k,
                      attn=None):
    import jax
    import numpy as np

    from deepspeedsyclsupport_tpu.inference.v2 import InferenceEngineV2
    from deepspeedsyclsupport_tpu.models import build_model, get_config

    cfg = get_config(model_name, max_seq_len=max_context)
    model = build_model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    prompts = [[int(t) for t in rng.randint(1, cfg.vocab_size - 1,
                                            size=prompt_len)]
               for _ in range(n_clients)]

    extra = _attn_overrides(attn)

    def run(k):
        eng = InferenceEngineV2(model, params,
                                config={"max_tokens_per_batch":
                                        max(256, prompt_len),
                                        "block_size": block_size,
                                        "max_context": max_context,
                                        "max_sequences": n_clients,
                                        "num_blocks": n_clients
                                        * (max_context // block_size),
                                        "decode_steps_per_dispatch": k,
                                        **extra})
        eng.warmup()
        outs = eng.generate(prompts, max_new_tokens=gen_len)  # compile path
        eng.host_dispatches = 0
        t0 = time.perf_counter()
        outs = eng.generate(prompts, max_new_tokens=gen_len)
        wall = time.perf_counter() - t0
        toks = sum(len(o) for o in outs)
        return {"tok_s": round(toks / wall, 1), "wall_s": round(wall, 3),
                "tokens": toks,
                "host_dispatches_per_token":
                    round(eng.host_dispatches / max(toks, 1), 4),
                "host_ms_per_token": round(wall / max(toks, 1) * 1e3, 3)}, \
            [list(map(int, o)) for o in outs]

    per_tok, toks_a = run(1)
    fused, toks_b = run(fused_k)
    assert toks_a == toks_b, "fused decode changed greedy outputs"
    speedup = fused["tok_s"] / max(per_tok["tok_s"], 1e-9)
    return {
        "metric": f"serve_fused_decode_{model_name}",
        "value": fused["tok_s"],
        "unit": "tokens/s",
        "vs_baseline": round(speedup, 3),
        "detail": {"platform": platform, "model": model_name,
                   "clients": n_clients, "gen_len": gen_len,
                   "attn_impl": attn or "auto",
                   "decode_steps_per_dispatch": fused_k,
                   "per_token_dispatch": per_tok, "fused": fused,
                   "greedy_outputs_identical": True,
                   "baseline": "fused-vs-per-token decode throughput ratio "
                               "(host-dispatch amortization; >1 is the "
                               "win, tunnel latency makes it bigger on "
                               "the real chip)"},
    }


def run_serve_fused():
    jax = _child_jax()

    platform = jax.devices()[0].platform
    if platform == "tpu":
        ladder = [
            dict(model_name="llama2-1b", n_clients=16, prompt_len=64,
                 gen_len=64, block_size=64, max_context=256, fused_k=16),
            dict(model_name="llama-650m", n_clients=16, prompt_len=64,
                 gen_len=64, block_size=64, max_context=256, fused_k=16),
            # XLA fallback if the Pallas serving path trips remote Mosaic
            dict(model_name="llama-650m", n_clients=16, prompt_len=64,
                 gen_len=64, block_size=64, max_context=256, fused_k=16,
                 attn="xla"),
            dict(model_name="tiny", n_clients=16, prompt_len=64,
                 gen_len=64, block_size=64, max_context=256, fused_k=16),
        ]
    else:
        ladder = [
            dict(model_name="tiny", n_clients=16, prompt_len=48,
                 gen_len=48, block_size=16, max_context=128, fused_k=16),
        ]
    last_err = None
    for cfg in ladder:
        try:
            _emit(_serve_fused_once(platform=platform, **cfg))
            return
        except Exception as e:
            last_err = (f"{cfg['model_name']}[{cfg.get('attn') or 'auto'}]: "
                        f"{str(e)[:300]}")
            print(f"serve_fused rung failed: {last_err}", file=sys.stderr)
            jax.clear_caches()
    raise RuntimeError(f"all serve_fused rungs failed; last: {last_err}")


# ==================================================================
# rung: kernels_aot (hardware-free accumulating evidence: per-kernel TPU
# Mosaic artifact hashes + cost-model roofline projections — VERDICT r4 #2)
# ==================================================================
V5E_PEAK_FLOPS = 197e12   # bf16 MXU
V5E_PEAK_BW = 819e9       # HBM bytes/s


def run_kernels_aot():
    import hashlib

    jax = _child_jax()
    import jax.numpy as jnp
    from jax import export as jexport

    from deepspeedsyclsupport_tpu.ops.flash_attention import flash_attention
    from deepspeedsyclsupport_tpu.ops.paged_attention import (
        paged_decode_attention_pallas, ragged_prefill_attention_pallas)

    B, S, H, D, KVH = 4, 2048, 16, 128, 4
    bs, slots, bps, nseq = 64, 8192, 16, 16

    def sds(shape, dtype=jnp.bfloat16):
        return jax.ShapeDtypeStruct(shape, dtype)

    def grad_of(f):
        return jax.grad(lambda q, k, v: f(q, k, v).astype(jnp.float32).sum(),
                        argnums=(0, 1, 2))

    flash = lambda q, k, v: flash_attention(q, k, v, causal=True,
                                            interpret=False)
    decode = lambda q, kc, vc, bt, sl: paged_decode_attention_pallas(
        q, kc, vc, bt, sl, block_size=bs)
    # analytic flop/byte models (Pallas can't host-lower for XLA cost
    # analysis off-TPU; these are the standard attention roofline counts)
    ctx = bps * bs
    fwd_flops = 4 * B * H * S * S * D * 0.5          # QK+PV, causal half
    fwd_bytes = 2 * B * S * (2 * H + 2 * KVH) * D    # bf16 q,k,v,out
    entries = [
        ("flash_fwd", flash,
         (sds((B, S, H, D)), sds((B, S, KVH, D)), sds((B, S, KVH, D))),
         fwd_flops, fwd_bytes),
        ("flash_bwd", grad_of(flash),
         (sds((B, S, H, D)), sds((B, S, KVH, D)), sds((B, S, KVH, D))),
         2.5 * fwd_flops, 2 * fwd_bytes),             # 5 matmuls vs 2
        ("paged_decode", decode,
         (sds((nseq, H, D)), sds((slots, KVH, D)), sds((slots, KVH, D)),
          sds((nseq, bps), jnp.int32), sds((nseq,), jnp.int32)),
         4 * nseq * H * ctx * D,
         2 * nseq * ctx * 2 * KVH * D),               # KV stream dominates
        ("ragged_prefill",
         lambda q, kc, vc, at, p0, ql: ragged_prefill_attention_pallas(
             q, kc, vc, at, p0, ql, block_size=bs),
         (sds((nseq, 128, H, D)), sds((slots, KVH, D)),
          sds((slots, KVH, D)), sds((nseq, bps), jnp.int32),
          sds((nseq,), jnp.int32), sds((nseq,), jnp.int32)),
         4 * nseq * H * 128 * ctx * D * 0.5,
         2 * nseq * ctx * 2 * KVH * D),
    ]
    kernels = {}
    for name, fn, args, flops, bytes_ in entries:
        exp = jexport.export(jax.jit(fn), platforms=["tpu"])(*args)
        digest = hashlib.sha256(exp.mlir_module_serialized).hexdigest()[:16]
        t_roof = max(flops / V5E_PEAK_FLOPS, bytes_ / V5E_PEAK_BW, 1e-12)
        kernels[name] = {
            "mosaic_artifact_sha256_16": digest,
            "cost_flops": flops,
            "cost_bytes": bytes_,
            "roofline_bound": ("compute" if flops / V5E_PEAK_FLOPS
                               >= bytes_ / V5E_PEAK_BW else "memory"),
            "projected_tflops": round(flops / t_roof / 1e12, 1),
            "projected_peak_frac": round(flops / t_roof / V5E_PEAK_FLOPS, 3),
        }
    proj = kernels["flash_fwd"]["projected_peak_frac"]
    _emit({"metric": "kernel_aot_evidence", "value": float(len(kernels)),
           "unit": "kernels",
           "vs_baseline": round(proj / 0.54, 4),
           "detail": {"platform": "aot",
                      "note": "PROJECTION from analytic flop/byte counts "
                              "at v5e roofline peaks — not a measurement; "
                              "artifact hashes prove the Mosaic lowering "
                              "compiled",
                      "v5e_peaks": {"bf16_flops": V5E_PEAK_FLOPS,
                                    "hbm_bytes_s": V5E_PEAK_BW},
                      "kernels": kernels,
                      "baseline": "projected flash-fwd peak fraction vs "
                                  "the reference 54% MFU bar"}})


def run_serve():
    jax = _child_jax()

    platform = jax.devices()[0].platform
    if platform == "tpu":
        ladder = [
            # the train flagship serves too: llama2-1b KV pool at 16
            # clients is ~4.3GB + 2.6GB weights on a 16GB v5e
            dict(model_name="llama2-1b", n_clients=16, reqs_per_client=2,
                 prompt_len=512, gen_len=64, budget=768, block_size=64,
                 max_context=1024),
            # 16 clients: the reference's SLA benchmark scale
            # (blogs/deepspeed-fastgen/README.md:177, Figure 5)
            dict(model_name="llama-650m", n_clients=16, reqs_per_client=2,
                 prompt_len=512, gen_len=64, budget=768, block_size=64,
                 max_context=1024),
            # XLA-attention fallback: if the Pallas serving path trips the
            # remote Mosaic compiler (opaque HTTP 500 in r5), still bank a
            # real-TPU serving number on the headline model
            dict(model_name="llama-650m", n_clients=16, reqs_per_client=2,
                 prompt_len=512, gen_len=64, budget=768, block_size=64,
                 max_context=1024, attn="xla"),
            # 8-client fallback keeps the headline MODEL comparable with
            # earlier rounds if the doubled KV pool does not fit
            dict(model_name="llama-650m", n_clients=8, reqs_per_client=2,
                 prompt_len=512, gen_len=64, budget=768, block_size=64,
                 max_context=1024),
            dict(model_name="tiny", n_clients=8, reqs_per_client=2,
                 prompt_len=512, gen_len=64, budget=768, block_size=64,
                 max_context=1024),
        ]
    else:
        ladder = [
            dict(model_name="tiny", n_clients=4, reqs_per_client=2,
                 prompt_len=48, gen_len=12, budget=64, block_size=16,
                 max_context=128),
        ]
    # ONE budget for the whole rung, carved across ladder retries — a
    # fresh per-config budget could legally outlive the parent's _spawn
    # timeout and turn back into the buffered-results kill this fixes
    rung_end = time.monotonic() + float(
        os.environ.get("DSTPU_SERVE_RUNG_BUDGET", 400))
    last_err = None
    for cfg in ladder:
        remaining = rung_end - time.monotonic()
        if remaining < 30:
            last_err = f"{cfg['model_name']}: skipped (rung budget)"
            break
        try:
            _emit(_serve_once(platform=platform,
                              scenario_budget_s=remaining / 2,  # two arms
                              **cfg))
            return
        except Exception as e:
            last_err = (f"{cfg['model_name']}[{cfg.get('attn') or 'auto'}]: "
                        f"{str(e)[:300]}")
            print(f"serve rung failed: {last_err}", file=sys.stderr)
            jax.clear_caches()
    raise RuntimeError(f"all serve rungs failed; last: {last_err}")


# ======================================================================
# parent orchestration
# ======================================================================
def _parse_lines(text):
    results = []
    for line in (text or "").strip().splitlines():
        try:
            parsed = json.loads(line)
            if isinstance(parsed, dict) and "metric" in parsed:
                results.append(parsed)
        except json.JSONDecodeError:
            continue
    return results


def _spawn(rung, timeout, env_overrides):
    """Run one rung child. Returns (results, err) — BOTH can be non-empty: a
    child that banked some JSON lines and then died/hung keeps its partial
    results AND reports the failure."""
    env = dict(os.environ)
    env[RUNG_ENV] = rung
    env.update(env_overrides)
    # Popen + communicate instead of subprocess.run: run() handles ONLY
    # TimeoutExpired with output capture — any other exception (the
    # SIGTERM handler's _Killed, notably) kills the child and closes the
    # pipes without draining them, losing every partial line the child
    # already flushed. The kill path below drains first and hangs the
    # salvaged results on the exception for main()'s aggregate flush.
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, env=env)
    try:
        out, err_txt = proc.communicate(timeout=timeout)
    except BaseException as exc:
        proc.kill()
        try:
            out, _ = proc.communicate(timeout=10)
        except _Killed as killed:
            # SIGTERM landed during the drain itself (e.g. while handling
            # a rung timeout): the kill outranks whatever got us here — it
            # must reach main()'s aggregate flush, not be swallowed. The
            # child is already SIGKILLed, so one bounded retry recovers the
            # pipe content (communicate() keeps partial output across an
            # interrupted call and allows retrying).
            try:
                out, _ = proc.communicate(timeout=2)
            except BaseException:
                out = ""
            killed.results = _parse_lines(out)
            killed.rung = rung
            raise
        except BaseException:
            out = ""
        results = _parse_lines(out)
        if isinstance(exc, subprocess.TimeoutExpired):
            return results, f"{rung}: timeout after {timeout}s"
        if isinstance(exc, _Killed):
            exc.results = results
            exc.rung = rung
        raise
    results = _parse_lines(out)

    def diag():
        """Prefer the exception over trailing log spam: the last
        'rung failed:'/Traceback block of stderr, else raw tails."""
        txt = err_txt or ""
        for marker in ("rung failed:", "Traceback (most recent call last)"):
            i = txt.rfind(marker)
            if i >= 0:
                return txt[i:i + 1200]
        return (txt[-1000:] + (out or "")[-300:])

    if proc.returncode != 0:
        return results, f"{rung}: rc={proc.returncode}: {diag()}"
    if not results:
        return results, f"{rung}: no metric emitted: {diag()}"
    return results, None


CPU_ENV = {"JAX_PLATFORMS": "cpu", "DSTPU_ACCELERATOR": "cpu"}


class _ProbeWatcher:
    """Background tunnel watcher (VERDICT r4 #2: the serial escalating
    probe ladder burned ~12.5 min of a dead-tunnel window). One cheap probe
    up front; if the tunnel is down, a daemon thread keeps re-probing
    CONCURRENTLY with the CPU rungs, and the main loop switches to the TPU
    plan the moment a probe lands. Probe wall-time on the main thread is a
    single 45 s attempt."""

    def __init__(self):
        import threading

        self.attempts = []
        self.found = threading.Event()
        self._stop = threading.Event()
        self._thread = None

    def probe_once(self, timeout):
        t0 = time.monotonic()
        res, err = _spawn("probe", timeout, {})
        plat = (res[0]["detail"].get("platform", "cpu") if res else None)
        self.attempts.append({
            "timeout_s": timeout,
            "elapsed_s": round(time.monotonic() - t0, 1),
            "outcome": plat or (err or "no output").split("\n")[0][:160]})
        if plat == "tpu":
            self.found.set()
        return plat

    def start_background(self, deadline):
        import threading

        def loop():
            while (not self._stop.is_set() and not self.found.is_set()
                   and deadline - time.monotonic() > 120):
                self.probe_once(60)
                self._stop.wait(30)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()


# multichip is the CPU virtual-device sim by construction — it runs under
# CPU_ENV on both plans (on a TPU window it still measures the SPMD sim,
# not the silicon, and is priced accordingly at the tail of the plan)
# train_ring is likewise CPU-sim by construction: it needs a 2-virtual-
# device seq mesh (forced host platform device count), and its flash arm
# runs the Pallas kernels in interpret mode off-TPU — an A/B of dispatch
# structure under the MFU ledger, not of kernel speed
RING_ENV = {**CPU_ENV,
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2"}
TPU_PLAN = [("kernels_micro", 400, {}, False),
            ("kernels", 600, {}, False),
            ("train", 1200, {}, True),
            ("serve", 700, {}, True),
            ("serve_fused", 500, {}, True),
            ("serve_prefix", 400, {}, True),
            ("serve_goodput", 700, {}, True),
            ("multichip", 400, CPU_ENV, False),
            ("offload", 500, CPU_ENV, False),
            ("fleet", 500, CPU_ENV, False),
            ("train_ring", 500, RING_ENV, False)]
CPU_PLAN = [("kernels_aot", 400, CPU_ENV, False),
            ("serve", 500, CPU_ENV, False),
            ("serve_fused", 400, CPU_ENV, False),
            ("serve_prefix", 400, CPU_ENV, False),
            ("serve_goodput", 700, CPU_ENV, False),
            ("train", 700, CPU_ENV, False),
            ("multichip", 400, CPU_ENV, False),
            ("offload", 500, CPU_ENV, False),
            ("fleet", 500, CPU_ENV, False),
            ("train_ring", 500, RING_ENV, False)]


class _Killed(Exception):
    """Raised from the SIGTERM handler: the outer harness' `timeout` sends
    SIGTERM before SIGKILL (rc=124). Raising is the only way to interrupt a
    blocking subprocess.run wait, and the whole point is to reach the
    aggregate-flush path below with whatever results exist — the r05
    failure was dying with every rung line buffered in children."""


def _bench_diff_gate(all_results):
    """Round-over-round regression gate: diff this round's in-memory
    metric lines against the newest checked-in ``BENCH_r*.json`` with
    ``tools/bench_diff.py`` and print ONE ``BENCH_DIFF`` verdict line
    (partial per-scenario lines are exempt inside diff_rounds). Advisory
    by contract — the bench always exits 0; the verdict line and the
    ``bench_diff`` block on the aggregate are what a round script gates
    on. Returns the summary dict, or None when no baseline exists."""
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    try:
        sys.path.insert(0, os.path.join(here, "tools"))
        import bench_diff as bd
    except Exception as e:  # the gate must never take the bench down
        print(f"BENCH_DIFF skipped: tools/bench_diff.py unusable ({e})",
              file=sys.stderr)
        return None
    finally:
        sys.path.pop(0)
    rounds = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
    if not rounds:
        print("BENCH_DIFF skipped: no prior BENCH_r*.json baseline")
        return None
    prev = rounds[-1]
    old = bd.load_round(prev)
    new = {}
    for r in all_results:
        bd._ingest(r, new)
    if not old or not new:
        print(f"BENCH_DIFF skipped: empty "
              f"{'baseline' if not old else 'round'}")
        return None
    threshold = float(os.environ.get("DSTPU_BENCH_DIFF_THRESHOLD", "0.10"))
    try:
        diff = bd.diff_rounds(old, new, threshold)
    except Exception as e:
        print(f"BENCH_DIFF skipped: diff failed ({e})", file=sys.stderr)
        return None
    regs = diff["regressions"]
    verdict = "REGRESSED" if regs else "OK"
    print(f"BENCH_DIFF {verdict} vs {os.path.basename(prev)} "
          f"(threshold {threshold:.0%}): "
          + (", ".join(regs) if regs else "no regressions beyond threshold"))
    return {"baseline": os.path.basename(prev), "threshold": threshold,
            "verdict": verdict, "regressions": regs,
            "metrics_compared": sum(1 for r in diff["rows"]
                                    if r.get("status") not in
                                    ("added", "removed"))}


def main():
    import signal

    def _on_term(signum, frame):
        raise _Killed(signum)

    signal.signal(signal.SIGTERM, _on_term)
    deadline = time.monotonic() + float(
        os.environ.get("DSTPU_BENCH_DEADLINE", 3300))
    all_results, errors = [], []
    watcher = _ProbeWatcher()

    def tier(env):
        return "cpu" if env else "tpu"

    plan = []
    degraded = False
    # the try must start HERE, not at the rung loop: the 45s TPU probe
    # below is exactly where an outer `timeout -s TERM ... 45` lands its
    # SIGTERM, and a _Killed escaping uncaught skips the aggregate flush
    # this handler exists to guarantee
    try:
        platform = watcher.probe_once(45) or "cpu"
        if platform != "tpu":
            errors.append(f"probe: {watcher.attempts[-1]['outcome']}")
            watcher.start_background(deadline)

        plan = list(TPU_PLAN if platform == "tpu" else CPU_PLAN)
        on_tpu = platform == "tpu"
        # done is keyed (rung, tier): a CPU run of a rung must NOT block
        # its TPU variant after a mid-window tunnel recovery — the TPU
        # numbers are the perf story, the CPU ones are the fallback
        done = set()

        while plan:
            # tunnel came up mid-window: switch to the TPU plan for the
            # remaining time (kernels first — bank silicon evidence)
            if not on_tpu and watcher.found.is_set():
                on_tpu = True
                platform = "tpu"
                plan = [p for p in TPU_PLAN if (p[0], "tpu") not in done]
                continue
            rung, timeout, env, cpu_retry = plan.pop(0)
            if (rung, tier(env)) in done:
                continue
            remaining = deadline - time.monotonic()
            if remaining < 60:
                errors.append(f"{rung}: skipped (deadline)")
                continue
            if degraded and not env:
                env, cpu_retry = CPU_ENV, False
                if rung.startswith("kernels"):
                    errors.append(f"{rung}: skipped (TPU degraded)")
                    continue
            results, err = _spawn(rung, min(timeout, remaining), env)
            done.add((rung, tier(env)))
            for r in results:
                _emit(r)
            all_results.extend(results)
            if err:
                errors.append(err)
                if not env:  # a TPU attempt failed
                    # only a TIMEOUT implicates the platform (hung tunnel) —
                    # a deterministic rung failure (rc!=0) must not cost the
                    # remaining rungs their TPU window
                    if "timeout" in err:
                        degraded = True
                    if cpu_retry and deadline - time.monotonic() > 120:
                        results, err2 = _spawn(
                            rung, min(600, deadline - time.monotonic()), CPU_ENV)
                        for r in results:
                            _emit(r)
                        all_results.extend(results)
                        if err2:
                            errors.append(err2)
            # the CPU plan finished but real window remains: idle-wait on the
            # watcher so a late tunnel still banks TPU evidence (the old
            # late-salvage path, now watcher-driven)
            if not plan and not on_tpu and not degraded:
                while (deadline - time.monotonic() > 360
                       and not watcher.found.is_set()):
                    time.sleep(20)
                if watcher.found.is_set():
                    on_tpu = True
                    platform = "tpu"
                    plan = [p for p in TPU_PLAN if (p[0], "tpu") not in done]
    except _Killed as e:
        # a second SIGTERM during the salvage emits below must not
        # interrupt them — ignore it before doing any more work
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        # a kill that landed mid-rung carries whatever the child had
        # flushed (salvaged by _spawn's drain) — bank it like any rung
        salvaged = getattr(e, "results", [])
        for r in salvaged:
            _emit(r)
        all_results.extend(salvaged)
        rung = getattr(e, "rung", None)
        if rung:
            errors.append(f"{rung}: killed mid-rung (SIGTERM)")
        errors.append(f"bench: SIGTERM ({e.args[0]}) — flushing "
                      f"partial aggregate (outer timeout imminent)")
    # the tail below IS the flush: a second SIGTERM must not interrupt it
    # (the outer timeout's SIGKILL is the backstop)
    signal.signal(signal.SIGTERM, signal.SIG_IGN)
    watcher.stop()
    probe_attempts = watcher.attempts

    # final aggregated headline: the train number if we have one, else
    # serve, else the best kernel line — with every rung under detail.rungs
    def best_train(lines):
        """The train rung A-Bs perf levers (attn impl, remat) — the best
        variant is the round's number. MFU ratios only compare within a
        platform, so prefer the TPU subset when it exists."""
        tpu = [r for r in lines
               if r.get("detail", {}).get("platform") == "tpu"]
        pool = tpu or lines
        return max(pool, key=lambda r: r.get("vs_baseline") or 0.0)

    def _is_partial(r):
        return bool((r.get("detail") or {}).get("partial"))

    def pick(prefix):
        # partial per-scenario flush lines (serve arms, goodput load
        # points) are evidence, not headlines — prefer a complete rung
        # line, fall back to a partial only when nothing else survived
        full = [r for r in all_results
                if r["metric"].startswith(prefix) and not _is_partial(r)]
        cands = full or [r for r in all_results
                         if r["metric"].startswith(prefix)]
        if not cands:
            return None
        if prefix == "train":
            return best_train(cands)
        return cands[0]

    head = pick("train") or pick("serve") or pick("kernel")
    if head is None:
        _emit({"metric": "train_tokens_per_sec_per_chip", "value": 0.0,
               "unit": "tokens/s", "vs_baseline": 0.0,
               "detail": {"platform": "none",
                          "probe_attempts": probe_attempts,
                          "errors": [e[-700:] for e in errors]}})
        return
    # prefer a REAL-TPU line as the headline over a CPU line of an
    # earlier-preferred rung (CPU train numbers are not the perf story)
    tpu_lines = [r for r in all_results
                 if r.get("detail", {}).get("platform") == "tpu"
                 and not _is_partial(r)]
    if head.get("detail", {}).get("platform") != "tpu" and tpu_lines:
        for prefix in ("train", "serve", "kernel"):
            cands = [r for r in tpu_lines
                     if r["metric"].startswith(prefix)]
            if cands:
                # same best-variant rule as pick() — not emission order
                head = best_train(cands) if prefix == "train" else cands[0]
                break
    rest = [r for r in all_results if r is not head]
    head = dict(head)
    head["detail"] = dict(head.get("detail", {}))
    head["detail"]["rungs"] = rest
    head["detail"]["probe_attempts"] = probe_attempts
    if errors:
        head["detail"]["rung_errors"] = [e[-700:] for e in errors]
    # round-over-round regression verdict vs the newest BENCH_r*.json
    # (tools/bench_diff.py; partial lines exempt) — printed AND attached
    try:
        bd_summary = _bench_diff_gate(all_results + [head])
    except Exception as e:
        bd_summary = None
        print(f"BENCH_DIFF skipped: {e}", file=sys.stderr)
    if bd_summary is not None:
        head["detail"]["bench_diff"] = bd_summary
    _emit(head)


if __name__ == "__main__":
    rung = os.environ.get(RUNG_ENV)
    if rung == "probe":
        run_probe()
    elif rung == "kernels_micro":
        run_kernels_micro()
    elif rung == "kernels":
        run_kernels()
    elif rung == "kernels_aot":
        run_kernels_aot()
    elif rung == "train":
        run_train()
    elif rung == "train_ring":
        run_train_ring()
    elif rung == "serve":
        run_serve()
    elif rung == "serve_fused":
        run_serve_fused()
    elif rung == "serve_prefix":
        run_serve_prefix()
    elif rung == "serve_goodput":
        run_serve_goodput()
    elif rung == "fleet":
        run_fleet()
    elif rung == "multichip":
        run_multichip()
    elif rung == "offload":
        run_offload()
    else:
        main()
        sys.exit(0)
