"""Serve with the ragged (FastGen-class) v2 engine.

    python examples/serve_fastgen.py                      # built-in tiny model
    python examples/serve_fastgen.py --hf /ckpts/llama-2-7b-hf
"""
import argparse

import jax
import numpy as np

from deepspeedsyclsupport_tpu.inference.v2 import (InferenceEngineV2,
                                                   build_hf_engine)
from deepspeedsyclsupport_tpu.models import build_model


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--hf", default=None,
                   help="local HF checkpoint directory (reference "
                        "build_hf_engine entry point)")
    p.add_argument("--max_new_tokens", type=int, default=16)
    args = p.parse_args()

    if args.hf:
        eng = build_hf_engine(args.hf, max_tokens_per_batch=768,
                              block_size=64, max_context=2048)
    else:
        model = build_model("tiny")
        eng = InferenceEngineV2(model, model.init_params(),
                                max_tokens_per_batch=64, block_size=16,
                                max_context=128, max_sequences=8,
                                max_prefill_fraction=0.75,
                                eviction_policy="lru")
    eng.warmup()

    # low-level contract: put/query/flush at single-forward granularity
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 500, size=n).tolist() for n in (12, 30, 7)]
    out = eng.put(list(range(len(prompts))), prompts)
    print("admitted:", out.admission.admitted,
          "rejected:", dict(out.admission.reasons))
    for uid in out:
        print(f"uid {uid}: first sampled token "
              f"{int(np.argmax(out[uid]))}")
    eng.flush(list(range(len(prompts))))

    # high-level continuous batching
    outs = eng.generate(prompts, max_new_tokens=args.max_new_tokens)
    for i, toks in enumerate(outs):
        print(f"prompt {i} -> {len(toks)} new tokens: {toks[:10]}...")


if __name__ == "__main__":
    main()
