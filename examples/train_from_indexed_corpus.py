"""Train from a Megatron-format indexed corpus with curriculum sampling.

Builds a tiny synthetic .bin/.idx corpus if none is given:

    python examples/train_from_indexed_corpus.py --steps 10
    python examples/train_from_indexed_corpus.py --data /corpora/pile_text_document
"""
import argparse

import jax
import numpy as np

import deepspeedsyclsupport_tpu as dstpu
from deepspeedsyclsupport_tpu.models import build_model
from deepspeedsyclsupport_tpu.runtime.data_pipeline.data_sampling import (
    DataAnalyzer, DSTpuDataSampler, MMapIndexedDataset,
    MMapIndexedDatasetBuilder, data_file_path, index_file_path)
from deepspeedsyclsupport_tpu.runtime.data_pipeline.data_sampling.data_sampler import (  # noqa: E501
    IndexedTokenBatches)
from deepspeedsyclsupport_tpu.runtime.dataloader import DSTpuDataLoader


def synth_corpus(prefix: str, n: int = 256, vocab: int = 512) -> str:
    rng = np.random.RandomState(0)
    b = MMapIndexedDatasetBuilder(data_file_path(prefix), dtype=np.int32)
    for _ in range(n):
        b.add_item(rng.randint(1, vocab, size=rng.randint(8, 65)))
    b.finalize(index_file_path(prefix))
    return prefix


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data", default=None,
                   help=".bin/.idx prefix (synthesized when absent)")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--seq_len", type=int, default=64)
    args = p.parse_args()

    prefix = args.data or synth_corpus("/tmp/dstpu_example_corpus")
    ds = MMapIndexedDataset(prefix)
    index = DataAnalyzer().run(ds)  # seqlen difficulty, free from the index

    model = build_model("tiny")
    engine, _, _, _ = dstpu.initialize(model=model, config={
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
    })
    sampler = DSTpuDataSampler(
        index,
        curriculum={"min_difficulty": 16, "max_difficulty": args.seq_len,
                    "schedule_type": "fixed_linear",
                    "schedule_config": {"total_curriculum_step": args.steps,
                                        "difficulty_step": 8}},
        micro_batch_size=engine.train_batch_size(), data_parallel_rank=0,
        data_parallel_size=1, total_steps=args.steps, seed=1)
    loader = DSTpuDataLoader(IndexedTokenBatches(ds, sampler, args.seq_len),
                             engine.topology)
    for step, batch in enumerate(loader):
        m = engine.train_batch(batch)
        loss = float(np.asarray(jax.device_get(m["loss"])))
        print(f"step {step:3d}  difficulty<= "
              f"{sampler.current_difficulty:3d}  loss {loss:.4f}")


if __name__ == "__main__":
    main()
