"""Train the flagship CausalLM with a reference-style JSON config.

    python examples/train_causal_lm.py --model tiny --steps 20
    python examples/train_causal_lm.py --config my_ds_config.json
"""
import argparse
import json

import jax
import numpy as np

import deepspeedsyclsupport_tpu as dstpu
from deepspeedsyclsupport_tpu.models import build_model


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="tiny",
                   help="models zoo preset (tiny/small/llama2-7b/...)")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--seq_len", type=int, default=128)
    p.add_argument("--config", default=None,
                   help="DeepSpeed-style JSON config path (overrides the "
                        "built-in demo config)")
    args = p.parse_args()

    config = json.load(open(args.config)) if args.config else {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-4}},
        "zero_optimization": {"stage": 2},
        "bf16": {"enabled": True},
        "activation_checkpointing": {"partition_activations": True},
        "gradient_clipping": 1.0,
        "steps_per_print": 5,
    }
    model = build_model(args.model)
    engine, _, _, _ = dstpu.initialize(model=model, config=config)

    rng = np.random.RandomState(0)
    for step in range(args.steps):
        ids = rng.randint(1, model.config.vocab_size,
                          size=(engine.train_batch_size(), args.seq_len))
        metrics = engine.train_batch({"input_ids": ids.astype(np.int32)})
        if step % 5 == 0 or step == args.steps - 1:
            loss = float(np.asarray(jax.device_get(metrics["loss"])))
            print(f"step {step:4d}  loss {loss:.4f}")
    engine.save_checkpoint("./ckpt", tag=f"step{args.steps}")
    print("checkpoint saved to ./ckpt")


if __name__ == "__main__":
    main()
